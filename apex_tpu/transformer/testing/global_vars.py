"""Global-variables registry — re-design of
``apex/transformer/testing/global_vars.py`` (get/set singleton pattern,
``global_vars.py:34-107``).

One ``set_global_variables(...)`` call wires the pieces the reference
registers separately: parsed args, the microbatch calculator, wall timers,
and an optional tensorboard writer. Accessors raise before initialization,
matching ``_ensure_var_is_initialized``.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.transformer import microbatches as _mb
from apex_tpu.transformer.pipeline_parallel.utils import Timers
from apex_tpu.transformer.testing import arguments as _args_mod

_GLOBAL_TIMERS: Optional[Timers] = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_ADLR_AUTORESUME = None


def get_args():
    """``global_vars.py:34``."""
    return _args_mod.get_args()


def get_num_microbatches() -> int:
    """``global_vars.py:40``."""
    return _mb.get_num_microbatches()


def get_current_global_batch_size() -> int:
    """``global_vars.py:44``."""
    return _mb.get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    """``global_vars.py:48``."""
    _mb.update_num_microbatches(consumed_samples, consistency_check)


def get_timers() -> Timers:
    """``global_vars.py:81``."""
    if _GLOBAL_TIMERS is None:
        raise RuntimeError("timers are not initialized")
    return _GLOBAL_TIMERS


def get_tensorboard_writer():
    """``global_vars.py:69`` — None unless the caller registered one."""
    return _GLOBAL_TENSORBOARD_WRITER


def get_adlr_autoresume():
    """``global_vars.py:75`` — the ADLR auto-resume stub; always None here
    (the reference's is an import probe for an NVIDIA-internal module)."""
    return _GLOBAL_ADLR_AUTORESUME


def set_global_variables(extra_args_provider=None, args_defaults=None,
                         args_list=None, ignore_unknown_args: bool = False):
    """``global_vars.py:87``: parse+validate args, then initialize the
    microbatch calculator and timers from them."""
    global _GLOBAL_TIMERS
    args = _args_mod.parse_args(
        extra_args_provider, args_list,
        defaults=args_defaults or {},
        ignore_unknown_args=ignore_unknown_args,
    )
    _args_mod.set_args(args)
    _mb.setup_microbatch_calculator(
        args.global_batch_size, args.micro_batch_size,
        args.data_parallel_size,
        rampup_batch_size=[int(x) for x in args.rampup_batch_size]
        if args.rampup_batch_size else None,
    )
    _GLOBAL_TIMERS = Timers()
    return args


def set_tensorboard_writer(writer) -> None:
    global _GLOBAL_TENSORBOARD_WRITER
    _GLOBAL_TENSORBOARD_WRITER = writer


def destroy_global_vars() -> None:
    global _GLOBAL_TIMERS, _GLOBAL_TENSORBOARD_WRITER
    _GLOBAL_TIMERS = None
    _GLOBAL_TENSORBOARD_WRITER = None
    _args_mod.set_args(None)
    # the calculator set_global_variables installed is global state too —
    # leaving it populated would let "destroyed" state answer
    # get_num_microbatches() with a stale value
    _mb._CALCULATOR = None
