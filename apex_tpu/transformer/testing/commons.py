"""Toy models for pipeline tests — re-design of
``apex/transformer/testing/commons.py:34-72`` (``MyModel`` with
``set_input_tensor``; here the stage function carries its input explicitly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class MyModel:
    """A square linear layer with optional activation: the reference's
    pipeline test stand-in (``commons.py:34``)."""

    def __init__(self, hidden_size: int, activation: bool = False):
        self.hidden_size = hidden_size
        self.activation = activation

    def init(self, key, dtype=jnp.float32) -> dict:
        return {
            "weight": jax.random.normal(key, (self.hidden_size, self.hidden_size), dtype)
            * (1.0 / self.hidden_size ** 0.5),
            "bias": jnp.zeros((self.hidden_size,), dtype),
        }

    def __call__(self, params: dict, x: jax.Array) -> jax.Array:
        y = x @ params["weight"] + params["bias"]
        return jnp.tanh(y) if self.activation else y


def model_provider_func(hidden_size: int, activation: bool = False) -> MyModel:
    """``model_provider_func`` (``commons.py``)."""
    return MyModel(hidden_size, activation)
