"""Megatron-style global argument parser — full-surface re-design of
``apex/transformer/testing/arguments.py`` (808 LoC).

Same argument groups, names, and defaults as the reference so Megatron-style
launch commands parse unchanged; the validation pass (``parse_args``'s
inline checks there) is :func:`validate_args`. TPU-native differences:

* world size comes from ``jax.device_count()`` (no ``RANK``/``WORLD_SIZE``
  env protocol — SPMD has one process), overridable for planning;
* ``params_dtype`` is a jnp dtype; bf16 is the native half type;
* knobs that only steer CUDA machinery (``--DDP-impl``, contiguous buffers,
  masked-softmax fusion) are accepted for command compatibility and recorded
  — the XLA compiler owns those decisions;
* TPU extensions: ``--context-parallel-size``, ``--sequence-parallel``.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

_GLOBAL_ARGS = None


def parse_args(extra_args_provider=None, args_list=None, *,
               defaults=None, ignore_unknown_args: bool = False,
               validate: bool = True):
    """Parse (and by default validate) the full Megatron argument surface."""
    parser = argparse.ArgumentParser(
        description="apex_tpu arguments", allow_abbrev=False)
    for add in (_add_network_size_args, _add_regularization_args,
                _add_training_args, _add_initialization_args,
                _add_learning_rate_args, _add_checkpointing_args,
                _add_mixed_precision_args, _add_distributed_args,
                _add_validation_args, _add_data_args, _add_logging_args):
        parser = add(parser)
    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        args, _ = parser.parse_known_args(args_list)
    else:
        args = parser.parse_args(args_list)
    if validate:
        validate_args(args, defaults or {})
    return args


def validate_args(args, defaults=None):
    """The reference's consistency pass: parallel-size arithmetic, dtype
    exclusivity, batch/virtual-stage divisibility, lr/seq sanity."""
    defaults = defaults or {}

    # -- distributed arithmetic (reference: world size env; here the mesh) --
    if args.world_size is None:
        args.world_size = jax.device_count()
    args.rank = 0  # SPMD: one controller process
    args.tensor_model_parallel_size = min(
        args.tensor_model_parallel_size, args.world_size)
    if args.world_size % args.tensor_model_parallel_size:
        raise ValueError(
            f"world size ({args.world_size}) is not divisible by tensor "
            f"model parallel size ({args.tensor_model_parallel_size})")
    args.pipeline_model_parallel_size = min(
        args.pipeline_model_parallel_size,
        args.world_size // args.tensor_model_parallel_size)
    mp = args.pipeline_model_parallel_size * args.tensor_model_parallel_size
    if args.world_size % mp:
        raise ValueError(
            f"world size ({args.world_size}) is not divisible by tensor "
            f"({args.tensor_model_parallel_size}) x pipeline "
            f"({args.pipeline_model_parallel_size}) parallel sizes")
    args.data_parallel_size = args.world_size // mp
    if args.pipeline_model_parallel_size > 1 \
            and args.pipeline_model_parallel_split_rank is not None \
            and args.pipeline_model_parallel_split_rank >= \
            args.pipeline_model_parallel_size:
        raise ValueError("split rank must be < pipeline model parallel size")

    # -- deprecated spellings (same guidance as the reference) --
    if getattr(args, "batch_size", None) is not None:
        raise ValueError("--batch-size is no longer valid, "
                         "use --micro-batch-size instead")
    if getattr(args, "warmup", None) is not None:
        raise ValueError("--warmup is no longer valid, "
                         "use --lr-warmup-fraction instead")
    if getattr(args, "model_parallel_size", None) is not None:
        raise ValueError("--model-parallel-size is no longer valid, "
                         "use --tensor-model-parallel-size instead")
    if args.checkpoint_activations:
        args.activations_checkpoint_method = "uniform"

    # -- user-supplied defaults (only fill Nones) --
    for key, val in defaults.items():
        if getattr(args, key, None) is None:
            setattr(args, key, val)

    # -- batch sizes / virtual stages --
    if args.micro_batch_size is None or args.micro_batch_size <= 0:
        raise ValueError("--micro-batch-size must be positive")
    if args.global_batch_size is None:
        args.global_batch_size = args.micro_batch_size * args.data_parallel_size
    if args.num_layers_per_virtual_pipeline_stage is not None:
        if args.pipeline_model_parallel_size <= 2:
            raise ValueError("interleaved schedule needs pipeline size > 2")
        if args.num_layers % args.num_layers_per_virtual_pipeline_stage:
            raise ValueError("num layers not divisible by layers per "
                             "virtual pipeline stage")
        args.virtual_pipeline_model_parallel_size = (
            (args.num_layers // args.pipeline_model_parallel_size)
            // args.num_layers_per_virtual_pipeline_stage)
    else:
        args.virtual_pipeline_model_parallel_size = None

    # -- parameter dtype --
    if args.fp16 and args.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    args.params_dtype = jnp.float32
    if args.fp16:
        args.params_dtype = jnp.float16
    if args.bf16:
        args.params_dtype = jnp.bfloat16
        # bf16 grads accumulate/all-reduce in fp32 (reference forces this)
        args.accumulate_allreduce_grads_in_fp32 = True

    args.consumed_train_samples = 0
    args.consumed_valid_samples = 0

    # -- iteration- vs sample-based training exclusivity --
    if args.train_iters:
        if args.train_samples is not None:
            raise ValueError("iteration-based training excludes --train-samples")
        if args.lr_decay_samples is not None:
            raise ValueError("iteration-based training excludes lr decay samples")
        if args.lr_warmup_samples != 0:
            raise ValueError("iteration-based training excludes lr warmup samples")
        if args.rampup_batch_size is not None:
            raise ValueError("iteration-based training excludes batch rampup")
        if args.lr_warmup_fraction is not None and args.lr_warmup_iters != 0:
            raise ValueError(
                "specify only one of lr-warmup-fraction and lr-warmup-iters")
    if args.train_samples:
        if args.train_iters is not None:
            raise ValueError("sample-based training excludes --train-iters")
        if args.lr_decay_iters is not None:
            raise ValueError("sample-based training excludes lr decay iters")
        if args.lr_warmup_iters != 0:
            raise ValueError("sample-based training excludes lr warmup iters")
        if args.lr_warmup_fraction is not None and args.lr_warmup_samples != 0:
            raise ValueError(
                "specify only one of lr-warmup-fraction and lr-warmup-samples")

    # -- required / derived model dims --
    for req in ("num_layers", "hidden_size", "num_attention_heads",
                "max_position_embeddings"):
        if getattr(args, req) is None:
            raise ValueError(f"{req} argument is None")
    if args.ffn_hidden_size is None:
        args.ffn_hidden_size = 4 * args.hidden_size
    if args.kv_channels is None:
        if args.hidden_size % args.num_attention_heads:
            raise ValueError("hidden size not divisible by attention heads")
        args.kv_channels = args.hidden_size // args.num_attention_heads
    if args.seq_length is not None:
        if args.encoder_seq_length is not None:
            raise ValueError("specify only one of seq-length and "
                             "encoder-seq-length")
        args.encoder_seq_length = args.seq_length
    else:
        if args.encoder_seq_length is None:
            raise ValueError("one of --seq-length / --encoder-seq-length "
                             "is required")
        args.seq_length = args.encoder_seq_length
    if args.seq_length and args.max_position_embeddings < args.seq_length:
        raise ValueError("max position embeddings < sequence length")
    if args.decoder_seq_length is not None \
            and args.max_position_embeddings < args.decoder_seq_length:
        raise ValueError("max position embeddings < decoder sequence length")
    if args.lr is not None and args.min_lr > args.lr:
        raise ValueError("min lr > lr")
    if args.save is not None and args.save_interval is None:
        raise ValueError("--save needs --save-interval")
    if args.fp16_lm_cross_entropy and not args.fp16:
        raise ValueError("fp16 lm cross entropy requires --fp16")
    if args.fp32_residual_connection and not (args.fp16 or args.bf16):
        raise ValueError("fp32 residual connection requires fp16/bf16")

    # -- vocab padding (make-vocab-size-divisible-by x tp) --
    if getattr(args, "vocab_size", None) is not None \
            and getattr(args, "padded_vocab_size", None) is None:
        mult = args.make_vocab_size_divisible_by * \
            args.tensor_model_parallel_size
        args.padded_vocab_size = ((args.vocab_size + mult - 1) // mult) * mult
    return args


# --- argument groups (names/defaults mirror the reference) -------------------

def _add_network_size_args(parser):
    g = parser.add_argument_group(title="network size")
    g.add_argument("--num-layers", type=int, default=None)
    g.add_argument("--hidden-size", type=int, default=None)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--num-attention-heads", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--vocab-size", type=int, default=None)
    g.add_argument("--padded-vocab-size", type=int, default=None)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--apply-residual-connection-post-layernorm",
                   action="store_true")
    g.add_argument("--openai-gelu", action="store_true")
    g.add_argument("--onnx-safe", type=bool, default=None)
    return parser


def _add_logging_args(parser):
    g = parser.add_argument_group(title="logging")
    g.add_argument("--log-params-norm", action="store_true")
    g.add_argument("--log-num-zeros-in-grad", action="store_true")
    g.add_argument("--tensorboard-log-interval", type=int, default=1)
    g.add_argument("--tensorboard-queue-size", type=int, default=1000)
    g.add_argument("--log-timers-to-tensorboard", action="store_true")
    g.add_argument("--log-batch-size-to-tensorboard", action="store_true")
    g.add_argument("--log-learning-rate-to-tensorboard", action="store_false")
    g.add_argument("--log-loss-scale-to-tensorboard", action="store_false")
    g.add_argument("--log-validation-ppl-to-tensorboard", action="store_true")
    return parser


def _add_regularization_args(parser):
    g = parser.add_argument_group(title="regularization")
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)
    return parser


def _add_training_args(parser):
    g = parser.add_argument_group(title="training")
    g.add_argument("--micro-batch-size", type=int, default=None)
    g.add_argument("--batch-size", type=int, default=None,
                   help="deprecated; use --micro-batch-size")
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)
    g.add_argument("--checkpoint-activations", action="store_true")
    g.add_argument("--activations-checkpoint-method", type=str, default=None,
                   choices=["uniform", "block"])
    g.add_argument("--activations-checkpoint-num-layers", type=int, default=1)
    g.add_argument("--distribute-checkpointed-activations",
                   action="store_true")
    g.add_argument("--train-iters", type=int, default=None)
    g.add_argument("--train-samples", type=int, default=None)
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--exit-duration-in-mins", type=int, default=None)
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "sgd"])
    g.add_argument("--dataloader-type", type=str, default=None,
                   choices=["single", "cyclic"])
    # CUDA-machinery knobs, accepted for command compat; XLA owns fusion
    g.add_argument("--no-async-tensor-model-parallel-allreduce",
                   action="store_true")
    g.add_argument("--no-persist-layer-norm", action="store_true")
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--no-gradient-accumulation-fusion", action="store_true")
    return parser


def _add_initialization_args(parser):
    g = parser.add_argument_group(title="initialization")
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--init-method-xavier-uniform", action="store_true")
    return parser


def _add_learning_rate_args(parser):
    g = parser.add_argument_group(title="learning rate")
    g.add_argument("--lr", type=float, default=None)
    g.add_argument("--lr-decay-style", type=str, default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-decay-samples", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--lr-warmup-iters", type=int, default=0)
    g.add_argument("--lr-warmup-samples", type=int, default=0)
    g.add_argument("--warmup", type=int, default=None,
                   help="deprecated; use --lr-warmup-fraction")
    g.add_argument("--min-lr", type=float, default=0.0)
    g.add_argument("--override-lr-scheduler", action="store_true")
    g.add_argument("--use-checkpoint-lr-scheduler", action="store_true")
    return parser


def _add_checkpointing_args(parser):
    g = parser.add_argument_group(title="checkpointing")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--no-save-optim", action="store_true", default=None)
    g.add_argument("--no-save-rng", action="store_true", default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--no-load-optim", action="store_true", default=None)
    g.add_argument("--no-load-rng", action="store_true", default=None)
    g.add_argument("--finetune", action="store_true")
    return parser


def _add_mixed_precision_args(parser):
    g = parser.add_argument_group(title="mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--fp32-residual-connection", action="store_true")
    g.add_argument("--no-query-key-layer-scaling", action="store_false",
                   dest="apply_query_key_layer_scaling")
    g.add_argument("--attention-softmax-in-fp32", action="store_true")
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    g.add_argument("--fp16-lm-cross-entropy", action="store_true")
    return parser


def _add_distributed_args(parser):
    g = parser.add_argument_group(title="distributed")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-split-rank", type=int,
                   default=None)
    g.add_argument("--model-parallel-size", type=int, default=None,
                   help="deprecated; use --tensor-model-parallel-size")
    g.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                   default=None)
    g.add_argument("--world-size", type=int, default=None,
                   help="default: jax.device_count()")
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--DDP-impl", default="local", choices=["local", "torch"],
                   help="accepted for compat; XLA handles grad allreduce")
    g.add_argument("--use-contiguous-buffers-in-local-ddp",
                   action="store_true", help="compat no-op (XLA fuses)")
    g.add_argument("--use-cpu-initialization", action="store_true",
                   default=None)
    return parser


def _add_validation_args(parser):
    g = parser.add_argument_group(title="validation")
    g.add_argument("--eval-iters", type=int, default=100)
    g.add_argument("--eval-interval", type=int, default=1000)
    return parser


def _add_data_args(parser):
    g = parser.add_argument_group(title="data and dataloader")
    g.add_argument("--data-path", nargs="*", default=None)
    g.add_argument("--split", type=str, default="969, 30, 1")
    g.add_argument("--vocab-file", type=str, default=None)
    g.add_argument("--merge-file", type=str, default=None)
    g.add_argument("--seq-length", type=int, default=None)
    g.add_argument("--encoder-seq-length", type=int, default=None)
    g.add_argument("--decoder-seq-length", type=int, default=None)
    g.add_argument("--retriever-seq-length", type=int, default=256)
    g.add_argument("--sample-rate", type=float, default=1.0)
    g.add_argument("--mask-prob", type=float, default=0.15)
    g.add_argument("--short-seq-prob", type=float, default=0.1)
    g.add_argument("--mmap-warmup", action="store_true")
    g.add_argument("--num-workers", type=int, default=2)
    g.add_argument("--reset-position-ids", action="store_true")
    g.add_argument("--reset-attention-mask", action="store_true")
    g.add_argument("--eod-mask-loss", action="store_true")
    return parser


def initialize_model_parallel_from_args(args, devices=None):
    """The launcher glue the reference spreads over its test/entry scripts:
    hand EVERY parsed parallelism flag — tp/pp/cp sizes, virtual-pp, and
    the encoder-decoder split rank — to ``initialize_model_parallel`` so
    every accepted flag actually changes execution. The mesh is built over
    ``args.world_size`` devices so ``args.data_parallel_size`` (set by
    ``validate_args``) agrees with the installed decomposition."""
    import jax

    from apex_tpu.parallel import mesh as mesh_lib

    if devices is None:
        devices = jax.devices()[:args.world_size]
    if len(devices) != args.world_size:
        raise ValueError(
            f"{len(devices)} device(s) do not match --world-size "
            f"{args.world_size}")
    return mesh_lib.initialize_model_parallel(
        tensor_model_parallel_size=args.tensor_model_parallel_size,
        pipeline_model_parallel_size=args.pipeline_model_parallel_size,
        context_parallel_size=getattr(args, "context_parallel_size", 1) or 1,
        virtual_pipeline_model_parallel_size=getattr(
            args, "virtual_pipeline_model_parallel_size", None),
        pipeline_model_parallel_split_rank=(
            args.pipeline_model_parallel_split_rank),
        devices=devices,
    )


# --- global singleton (global_vars.py get/set pattern) -----------------------

def set_args(args) -> None:
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = args


def get_args():
    if _GLOBAL_ARGS is None:
        raise RuntimeError(
            "arguments are not initialized; call set_args(parse_args())")
    return _GLOBAL_ARGS
