"""Megatron-style global argument parser.

Re-design of ``apex/transformer/testing/arguments.py`` (808 LoC) +
``global_vars.py:270``'s get/set singleton: the subset of arguments the
transformer stack actually consumes, with the same names and defaults, plus
the TPU-native extensions (context parallelism, sequence parallelism).
"""

from __future__ import annotations

import argparse
from typing import Optional

_GLOBAL_ARGS = None


def parse_args(extra_args_provider=None, args_list=None) -> argparse.Namespace:
    """``parse_args`` (``arguments.py``): model/train/parallel argument
    groups; unrecognized args error like the reference."""
    parser = argparse.ArgumentParser(description="apex_tpu arguments")

    g = parser.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=2)
    g.add_argument("--hidden-size", type=int, default=64)
    g.add_argument("--num-attention-heads", type=int, default=4)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=512)
    g.add_argument("--seq-length", type=int, default=128)
    g.add_argument("--vocab-size", type=int, default=1024)
    g.add_argument("--padded-vocab-size", type=int, default=None)

    g = parser.add_argument_group("train")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", type=int, default=None)
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--train-iters", type=int, default=10)
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2**16)
    g.add_argument("--seed", type=int, default=1234)

    g = parser.add_argument_group("parallel")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int, default=None)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--num-microbatches", type=int, default=None)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)
    args = parser.parse_args(args_list)

    if args.padded_vocab_size is None:
        # pad vocab to a multiple of 128*tp (the reference pads to
        # make-vocab-size-divisible-by x tp)
        mult = 128 * args.tensor_model_parallel_size
        args.padded_vocab_size = ((args.vocab_size + mult - 1) // mult) * mult
    if args.global_batch_size is None:
        args.global_batch_size = args.micro_batch_size
    if args.ffn_hidden_size is None:
        args.ffn_hidden_size = 4 * args.hidden_size
    return args


def set_args(args) -> None:
    """``set_global_variables`` analog (``global_vars.py``)."""
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = args


def get_args():
    """``get_args`` (``global_vars.py:270``)."""
    if _GLOBAL_ARGS is None:
        raise RuntimeError("arguments are not initialized; call set_args(parse_args())")
    return _GLOBAL_ARGS
