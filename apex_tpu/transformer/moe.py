"""Mixture-of-experts FFN with expert parallelism (TPU-first extension).

The reference has no MoE (SURVEY.md §2.3 lists EP as an absent strategy);
this fills the gap the TPU way: GShard/Switch-style static-capacity routing
expressed as einsums (XLA sees only fixed shapes — no ragged dispatch), with
experts sharded over a mesh axis and tokens exchanged by two
``lax.all_to_all``s, the same pattern Ulysses attention uses for heads.

Components:
* :func:`router_topk` — softmax gate + iterative top-k slot assignment with
  per-expert capacity, returning dense (tokens, E, C) dispatch/combine
  tensors; overflowing tokens are dropped (zero combine weight), underfull
  slots are zero-padded — both static-shape-friendly.
* :class:`MoEMLP` — per-expert two-layer FFN over the dispatched
  (E, C, d) blocks; batched einsum keeps every expert's GEMM on the MXU.
* :func:`moe_layer` — dispatch → (optional expert-parallel all_to_all) →
  experts → reverse all_to_all → combine; returns the output and the
  auxiliary losses (Switch load-balance, router z-loss).

Expert parallelism: run inside ``shard_map`` with ``axis_name`` bound (the
``dp`` axis by default — expert parallelism folds over data parallelism,
``apex_tpu.parallel.mesh.EXPERT_AXIS`` note). Each device hosts
``E // axis_size`` experts; the first all_to_all routes every device's
dispatched blocks to the experts' owners, the second routes results back.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# the router aux schema — THE definition every consumer zero-initializes
# from (gpt.hidden_states_with_aux, GPTPipeline._stage accumulate against
# this exact tree structure)
ROUTER_AUX_ZEROS = {"load_balance_loss": 0.0, "router_z_loss": 0.0,
                    "drop_fraction": 0.0}


def router_aux_zeros(dtype=None):
    """Fresh init tree matching :func:`router_topk_sparse`'s aux output."""
    return jax.tree.map(
        lambda v: jnp.full((), v, dtype or jnp.float32), ROUTER_AUX_ZEROS)


def router_topk_sparse(
    logits: jax.Array,
    capacity: int,
    k: int = 2,
    *,
    normalize_gates: bool = True,
    priority: str = "gate",
) -> Tuple[jax.Array, jax.Array, dict]:
    """Top-k token→expert assignment with capacity, SPARSE form.

    ``logits``: (T, E). Returns ``(slot_ids, gates, aux)``:

    * ``slot_ids`` (k, T) int32 — round r assigns token t to flat expert
      slot ``e·C + c``; dropped (over-capacity) assignments point at the
      sentinel slot ``E·C`` (a dump row the dispatch scatter writes into
      and the combine gather zero-weights);
    * ``gates`` (k, T) fp32 — the (optionally renormalized) combine
      weights, 0 for dropped assignments;
    * ``aux`` — ``load_balance_loss`` (Switch-style: E · Σ_e fraction_e ·
      mean-gate_e, 1.0 at uniform routing), ``router_z_loss``, and
      ``drop_fraction`` (share of the T·k assignments that overflowed —
      surfaced so training loops can alarm on routing collapse).

    The sparse form is what :func:`moe_layer` consumes: dispatch/combine
    become an O(T·d) row scatter/gather instead of the GShard one-hot
    einsum whose (T, E, C) tensors are quadratic in tokens — at the
    flagship scale (T=16k, E=8) those weigh 2.7 GB each and cost 5× the
    expert FFN's own FLOPs (measured OOM, PERF.md r3). Use
    :func:`router_topk` when the dense masks themselves are wanted.

    Slot assignment is k rounds of argmax with chosen gates masked out.
    ``priority`` decides who wins a full expert's last slots within a
    round: ``"gate"`` (default) ranks claimants by router confidence —
    the GShard/V-MoE "important tokens first" rule, removing the
    position-in-batch bias — while ``"token"`` keeps raw batch order (the
    Switch formulation; deterministic and marginally cheaper — no sort).
    All shapes static either way.
    """
    if priority not in ("gate", "token"):
        raise ValueError(f"priority must be gate|token, got {priority!r}")
    T, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    remaining = gates
    counts = jnp.zeros((E,), jnp.int32)
    gate_sum = jnp.zeros((T,), jnp.float32)
    first_choice = None
    dropped = jnp.zeros((), jnp.float32)
    slot_ids = []
    gate_rounds = []

    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)                    # (T,)
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.float32)      # (T, E)
        if first_choice is None:
            first_choice = onehot
        gate_round = jnp.sum(gates * onehot, axis=-1)              # (T,)
        if priority == "gate":
            # rank claimants by gate value: the slot cumsum runs in
            # confidence order, then scatters back to token order
            order = jnp.argsort(-gate_round)
            oh_sorted = onehot[order]
            pos = (jnp.cumsum(oh_sorted, axis=0) - 1.0) + counts[None, :]
            slot_sorted = jnp.sum(pos * oh_sorted, axis=-1)
            slot = jnp.zeros((T,), slot_sorted.dtype).at[order].set(
                slot_sorted)
        else:
            pos = (jnp.cumsum(onehot, axis=0) - 1.0) + counts[None, :]
            slot = jnp.sum(pos * onehot, axis=-1)                  # (T,)
        fits = slot < capacity
        flat = choice.astype(jnp.int32) * capacity + slot.astype(jnp.int32)
        slot_ids.append(jnp.where(fits, flat, E * capacity))
        gate_val = gate_round * fits                               # (T,)
        gate_rounds.append(gate_val)
        gate_sum = gate_sum + gate_val
        counts = counts + jnp.sum(onehot * fits[:, None], axis=0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)                     # mask chosen
        dropped = dropped + jnp.sum(1.0 - fits)

    gates_out = jnp.stack(gate_rounds)                             # (k, T)
    if normalize_gates:
        gates_out = gates_out / jnp.maximum(gate_sum, 1e-9)[None, :]

    # Switch load balance over the FIRST choice (the dominant assignment):
    # fraction of tokens routed to e x mean router prob for e, scaled by E.
    frac = jnp.mean(first_choice, axis=0)
    prob = jnp.mean(gates, axis=0)
    aux = {
        "load_balance_loss": E * jnp.sum(frac * prob),
        "router_z_loss": jnp.mean(jax.nn.logsumexp(
            logits.astype(jnp.float32), axis=-1) ** 2),
        "drop_fraction": dropped / float(T * k),
    }
    return jnp.stack(slot_ids), gates_out, aux


def router_topk(
    logits: jax.Array,
    capacity: int,
    k: int = 2,
    *,
    normalize_gates: bool = True,
    priority: str = "gate",
) -> Tuple[jax.Array, jax.Array, dict]:
    """Dense (GShard-mask) form of :func:`router_topk_sparse`: returns
    ``(dispatch (T, E, C) one-hot, combine (T, E, C) gate-weighted, aux)``.
    O(T·E·C) memory — fine for tests/small routing, quadratic in tokens at
    scale (prefer the sparse form `moe_layer` uses)."""
    T, E = logits.shape
    slot_ids, gates, aux = router_topk_sparse(
        logits, capacity, k, normalize_gates=normalize_gates,
        priority=priority)
    dispatch = jnp.zeros((T, E * capacity + 1), jnp.float32)
    combine = jnp.zeros((T, E * capacity + 1), jnp.float32)
    rows = jnp.arange(T)
    for r in range(slot_ids.shape[0]):
        dispatch = dispatch.at[rows, slot_ids[r]].add(1.0)
        combine = combine.at[rows, slot_ids[r]].add(gates[r])
    return (dispatch[:, :-1].reshape(T, E, capacity),
            combine[:, :-1].reshape(T, E, capacity), aux)


def slot_ids_are_unique(slot_ids, num_slots) -> jax.Array:
    """Debug invariant behind :func:`_slot_inverse` and the gather
    dispatch/combine VJPs: every real (< ``num_slots``) slot id appears AT
    MOST ONCE across all k rounds. :func:`router_topk_sparse` guarantees it
    (the per-expert slot cumsum carries ``counts`` across rounds, so two
    assignments can never land on the same (expert, position)); a future
    router emitting duplicates would silently drop tokens in the
    ``mode='drop'`` scatters and corrupt the hand-written VJPs. Returns a
    traced bool — assert it in tests / under a debug flag whenever the
    routing logic changes (tests/test_moe.py::TestRouter does)."""
    flat = slot_ids.reshape(-1)
    counts = jnp.zeros((num_slots + 1,), jnp.int32).at[
        jnp.clip(flat, 0, num_slots)].add(1)
    return jnp.all(counts[:num_slots] <= 1)


def _slot_inverse(slot_ids, gates, num_slots):
    """Invert the token→slot assignment: slot ids are UNIQUE across rounds
    (the slot cumsum carries counts over), so the (T, d) dispatch scatter is
    a permutation — invertible into (S,)-sized scalar scatters that cost
    1/512th of the row scatter they replace. Returns (inv (S,) int32 —
    which token fills each slot, valid (S,) bool — empty slots must
    contribute zeros). The per-slot gate value is NOT built here:
    `_gather_combine_bwd` derives it from its own residuals, keeping the
    inversion-by-scatter logic in exactly one consumer per quantity."""
    k, T = slot_ids.shape
    del gates
    inv = jnp.zeros((num_slots,), jnp.int32)
    valid = jnp.zeros((num_slots,), jnp.bool_)
    tok = jnp.arange(T, dtype=jnp.int32)
    for r in range(k):
        sid = slot_ids[r]  # dump assignments (== num_slots) drop out of range
        inv = inv.at[sid].set(tok, mode="drop")
        valid = valid.at[sid].set(True, mode="drop")
    return inv, valid


@jax.custom_vjp
def _gather_dispatch(xt, slot_ids, inv, valid):
    """(T, d) tokens → (S, d) expert slots, as a row GATHER both ways.

    The obvious formulation — ``buf.at[slot_ids].add(xt)`` — is an XLA row
    scatter, and its transpose (plus the remat re-forward) made the
    dispatch/combine pair cost ~62 ms/step at the flagship MoE shape
    (PERF.md r3): TPU scatters neither fuse nor pipeline the way gathers
    do. With the slot inverse precomputed, forward is ``xt[inv]`` masked by
    slot validity, and the hand-written VJP routes the cotangent back with
    the forward's own ``slot_ids`` gather — no (T, d)-sized scatter exists
    in either direction."""
    return jnp.where(valid[:, None], xt[inv], 0).astype(xt.dtype)


def _gather_dispatch_fwd(xt, slot_ids, inv, valid):
    return _gather_dispatch(xt, slot_ids, inv, valid), (slot_ids, inv.shape)


def _gather_dispatch_bwd(res, g):
    import numpy as np
    slot_ids, inv_shape = res
    gp = jnp.concatenate([g, jnp.zeros((1, g.shape[1]), g.dtype)], 0)
    dxt = gp[slot_ids[0]]
    for r in range(1, slot_ids.shape[0]):
        dxt = dxt + gp[slot_ids[r]]
    f0 = lambda s: np.zeros(s, jax.dtypes.float0)  # noqa: E731
    return dxt, f0(slot_ids.shape), f0(inv_shape), f0(inv_shape)


_gather_dispatch.defvjp(_gather_dispatch_fwd, _gather_dispatch_bwd)


@jax.custom_vjp
def _gather_combine(op, gates, slot_ids, inv, valid):
    """y_t = Σ_r gates_r(t) · op[slot_r(t)] with the dump row synthesized as
    a zero row; the VJP's d_op is a gather by ``inv`` (the scatter-free
    mirror of :func:`_gather_dispatch`)."""
    opp = jnp.concatenate([op, jnp.zeros((1, op.shape[1]), op.dtype)], 0)
    y = gates[0][:, None].astype(opp.dtype) * opp[slot_ids[0]]
    for r in range(1, gates.shape[0]):
        y = y + gates[r][:, None].astype(opp.dtype) * opp[slot_ids[r]]
    return y


def _gather_combine_fwd(op, gates, slot_ids, inv, valid):
    return (_gather_combine(op, gates, slot_ids, inv, valid),
            (op, gates, slot_ids, inv, valid))


def _gather_combine_bwd(res, dy):
    import numpy as np
    op, gates, slot_ids, inv, valid = res
    S = op.shape[0]
    gates_slot = jnp.zeros((S,), jnp.float32)
    for r in range(gates.shape[0]):
        gates_slot = gates_slot.at[slot_ids[r]].set(gates[r], mode="drop")
    d_op = (jnp.where(valid, gates_slot, 0.0)[:, None]
            * dy.astype(jnp.float32)[inv]).astype(op.dtype)
    opp = jnp.concatenate([op, jnp.zeros((1, op.shape[1]), op.dtype)], 0)
    dyf = dy.astype(jnp.float32)
    d_gates = jnp.stack([
        jnp.sum(dyf * opp[slot_ids[r]].astype(jnp.float32), axis=-1)
        for r in range(gates.shape[0])])
    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)  # noqa: E731
    return d_op, d_gates, f0(slot_ids), f0(inv), f0(valid)


_gather_combine.defvjp(_gather_combine_fwd, _gather_combine_bwd)


@dataclasses.dataclass
class MoEMLP:
    """Per-expert FFN bank (num_experts_local, hidden, ffn) — GEMMs stay
    batched over experts so the MXU sees (E·C, hidden) x (hidden, ffn).

    ``tp_size > 1``: each expert's FFN is tensor-parallel over its ffn dim
    (w1 column-sharded, w2 row-sharded — the same Col→Row split the dense
    ``ParallelMLP`` uses, reference ``standalone_gpt.py:236``); b2 is
    replicated and added after the tp reduce. Composes orthogonally with
    expert parallelism: ep shards *which experts* a device owns, tp shards
    *each expert's* GEMMs."""

    num_experts: int
    hidden: int
    ffn: int
    tp_size: int = 1

    @property
    def ffn_per_partition(self) -> int:
        if self.ffn % self.tp_size:
            raise ValueError(
                f"ffn ({self.ffn}) must be divisible by tp_size "
                f"({self.tp_size}) for tensor-parallel experts")
        return self.ffn // self.tp_size

    def init(self, key, rank: int = 0, dtype=jnp.float32):
        """This tp rank's shard. The full (tp=1) bank is generated and
        sliced so a per-rank init equals the corresponding slice of a
        replicated init (the ``shard_params_for_tp`` contract)."""
        k1, k2, k3 = jax.random.split(key, 3)
        s1 = (2.0 / self.hidden) ** 0.5
        s2 = (2.0 / self.ffn) ** 0.5
        fp = self.ffn_per_partition
        sl = slice(rank * fp, (rank + 1) * fp)
        w1 = jax.random.normal(
            k1, (self.num_experts, self.hidden, self.ffn), dtype) * s1
        w2 = jax.random.normal(
            k2, (self.num_experts, self.ffn, self.hidden), dtype) * s2
        return {
            "router": jax.random.normal(k3, (self.hidden, self.num_experts), dtype) * 0.02,
            "w1": w1[:, :, sl],
            "b1": jnp.zeros((self.num_experts, fp), dtype),
            "w2": w2[:, sl, :],
            "b2": jnp.zeros((self.num_experts, self.hidden), dtype),
        }


def _expert_ffn(params, x_ecd, tp_axis=None):
    """(E_local, C', d) through each expert's two-layer GELU FFN. With
    ``tp_axis`` the ffn dim is sharded over it: the input enters through
    copy-to-region (identity fwd, psum bwd) and the partial products leave
    through reduce-from-region (psum fwd, identity bwd) — the Megatron
    Col→Row collective placement, expert-batched."""
    from apex_tpu.transformer.tensor_parallel import mappings
    x_ecd = mappings.copy_to_tensor_model_parallel_region(x_ecd, tp_axis)
    h = jnp.einsum("ecd,edf->ecf", x_ecd, params["w1"]) + params["b1"][:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    y = jnp.einsum("ecf,efd->ecd", h, params["w2"])
    y = mappings.reduce_from_tensor_model_parallel_region(y, tp_axis)
    return y + params["b2"][:, None, :]


def moe_layer(
    params: dict,
    x: jax.Array,
    *,
    k: int = 2,
    capacity_factor: float = 1.25,
    axis_name: Optional[str] = None,
    tp_axis: Optional[str] = None,
    normalize_gates: bool = True,
    priority: str = "gate",
) -> Tuple[jax.Array, dict]:
    """MoE FFN over ``x`` (..., hidden); returns (y, aux_losses —
    including ``drop_fraction``, see :func:`router_topk`).

    With ``axis_name`` (inside shard_map): experts are sharded over the
    axis — ``params['w1']`` etc. hold this device's ``E_local`` experts and
    the router logits cover all ``E_local · axis_size`` experts. Dispatched
    blocks take one ``all_to_all`` to the expert owners and one back.

    With ``tp_axis``: each expert's ffn dim is additionally sharded over
    that axis (see :class:`MoEMLP`); routing/dispatch/combine run
    replicated across tp — only the expert GEMMs split.
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    T = xt.shape[0]

    ep = jax.lax.axis_size(axis_name) if axis_name else 1
    e_local = params["w1"].shape[0]
    E = e_local * ep
    if params["router"].shape[-1] != E:
        raise ValueError(
            f"router covers {params['router'].shape[-1]} experts but the "
            f"expert bank holds {e_local} x axis size {ep} = {E}")
    capacity = max(1, int(capacity_factor * k * T / E))

    logits = xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    slot_ids, gates, aux = router_topk_sparse(
        logits, capacity, k, normalize_gates=normalize_gates,
        priority=priority)

    # Dispatch/combine as row GATHERS in both directions (forward AND
    # cotangent): slot uniqueness makes the assignment a permutation, so
    # the slot→token inverse turns the O(T·d) row scatter — and the
    # scatter its transpose would emit — into gathers (custom VJPs above;
    # the scatter formulation cost ~62 ms/step at flagship MoE scale).
    # The GShard one-hot einsum both replace materialized (T, E, C) masks
    # — quadratic in tokens and 5× the expert FFN's own FLOPs (PERF.md r3).
    inv, valid = _slot_inverse(slot_ids, gates, E * capacity)
    expert_in = _gather_dispatch(xt, slot_ids, inv, valid
                                 ).reshape(E, capacity, d)

    if axis_name:
        # (E, C, d) -> (ep, e_local, C, d) -> a2a -> (e_local, ep*C, d):
        # each device gathers every peer's blocks for ITS experts
        blocks = expert_in.reshape(ep, e_local, capacity, d)
        blocks = jax.lax.all_to_all(blocks, axis_name, split_axis=0,
                                    concat_axis=2, tiled=True)
        out = _expert_ffn(params, blocks.reshape(e_local, ep * capacity, d),
                          tp_axis)
        out = out.reshape(1, e_local, ep * capacity, d)
        out = jax.lax.all_to_all(out, axis_name, split_axis=2,
                                 concat_axis=0, tiled=True)
        expert_out = out.reshape(E, capacity, d)
    else:
        expert_out = _expert_ffn(params, expert_in, tp_axis)

    y = _gather_combine(expert_out.reshape(E * capacity, d), gates,
                        slot_ids, inv, valid)
    return y.reshape(*lead, d).astype(x.dtype), aux
