"""``apex.transformer.functional`` parity namespace."""

from apex_tpu.ops.softmax import (scaled_masked_softmax,  # noqa: F401
                                  scaled_upper_triang_masked_softmax)
from apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    FusedScaleMaskSoftmax,
)
