"""``FusedScaleMaskSoftmax`` — the user-facing scale+mask+softmax module.

Parity surface for ``apex/transformer/functional/fused_softmax.py:101-207``,
re-designed for TPU. The reference routes between two CUDA kernels and a
torch fallback based on a table of warp-level constraints
(``is_kernel_available``: fp16/bf16 only, 16 < sk ≤ 2048, ``sq % 4 == 0``,
``b·np`` divisibility by an arch-dependent batch-per-block). Here the Pallas
kernel streams any sequence length, so eligibility collapses to lane
alignment (``sk % 128 == 0``) plus the user's fusion flag; everything else
falls back to the jnp composition with identical semantics (fp32 softmax for
half inputs when ``softmax_in_fp32``, result cast back).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import (scaled_masked_softmax,
                                  scaled_upper_triang_masked_softmax)
from apex_tpu.transformer.enums import AttnMaskType


class FusedScaleMaskSoftmax:
    """scale + mask + softmax over (b, np, sq, sk) attention scores.

    Arguments follow the reference module:

    * ``input_in_fp16`` / ``input_in_bf16`` — declared input precision
      (mutually exclusive; either enables the kernel path);
    * ``attn_mask_type`` — ``AttnMaskType.causal`` builds the upper-triangle
      mask in-kernel, ``AttnMaskType.padding`` applies the passed mask;
    * ``scaled_masked_softmax_fusion`` — user opt-in to the kernel;
    * ``mask_func`` — fallback-path masking function ``(scores, mask) ->
      masked`` (the kernel path applies masks natively);
    * ``softmax_in_fp32`` — fallback computes softmax in fp32 and casts back;
    * ``scale`` — optional score scaling (requires ``softmax_in_fp32``,
      matching the reference's constraint).
    """

    def __init__(
        self,
        input_in_fp16: bool,
        input_in_bf16: bool,
        attn_mask_type: AttnMaskType,
        scaled_masked_softmax_fusion: bool,
        mask_func: Optional[Callable],
        softmax_in_fp32: bool,
        scale: Optional[float],
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError(
                "both fp16 and bf16 flags cannot be active at the same time.")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if not (scale is None or softmax_in_fp32):
            raise RuntimeError("softmax should be in fp32 when scaled")
        if attn_mask_type not in (AttnMaskType.causal, AttnMaskType.padding):
            raise ValueError("Invalid attn_mask_type.")

    def __call__(self, scores: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
        assert scores.ndim == 4  # (b, np, sq, sk)
        if self.is_kernel_available(mask, *scores.shape):
            return self.forward_fused_softmax(scores, mask)
        return self.forward_jnp_softmax(scores, mask)

    def is_kernel_available(self, mask, b, nh, sq, sk) -> bool:
        """The reference's constraint table (fp16-only, ``16 < sk <= 2048``,
        warp divisibility — ``fused_softmax.py:159-179``) reduces to: user
        opted in, half-precision input, and a lane-aligned softmax axis.
        Notably there is NO upper sequence cap. Causal keeps the reference
        kernel's square-scores requirement
        (``scaled_upper_triang_masked_softmax.h`` assumes sq == sk);
        rectangular causal shapes take the fallback."""
        if self.attn_mask_type == AttnMaskType.causal and sq != sk:
            return False
        return bool(
            self.scaled_masked_softmax_fusion
            and self.input_in_float16
            and sk % 128 == 0
            and (mask is not None or self.attn_mask_type == AttnMaskType.causal)
        )

    def forward_fused_softmax(self, scores, mask):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            return scaled_upper_triang_masked_softmax(scores, scale)
        return scaled_masked_softmax(scores, mask, scale)

    def forward_jnp_softmax(self, scores, mask):
        """Fallback with the reference's dtype dance (`forward_torch_softmax`):
        fp32 softmax for half inputs when requested, cast back after."""
        orig = scores.dtype
        if self.input_in_float16 and self.softmax_in_fp32:
            scores = scores.astype(jnp.float32)
        if self.scale is not None:
            scores = scores * self.scale
        if mask is not None:
            # the reference calls mask_func unconditionally when a mask is
            # present (fused_softmax.py:193) — never drop a mask silently
            if self.mask_func is not None:
                scores = self.mask_func(scores, mask)
            else:
                scores = jnp.where(mask, -1e30, scores)
        if self.attn_mask_type == AttnMaskType.causal:
            # top-left alignment (row r sees cols <= r), the kernel path's
            # convention (ops/softmax.py:_xla_fwd) and the reference's
            # square-triangle semantics
            sq, sk = scores.shape[-2], scores.shape[-1]
            causal = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
            scores = jnp.where(causal, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        if self.input_in_float16 and self.softmax_in_fp32:
            probs = probs.astype(orig)
        return probs
