"""Token sampling for the decode loop.

Greedy, temperature, and top-k sampling over the last-position logits.
``temperature`` and ``top_k`` are STATIC (python numbers fixed at engine
construction): inside the jit'd ``decode_step`` they select the sampling
program once — the sampled path never branches at run time, which is part
of the zero-recompile contract (the alternative, traced sampling knobs,
would either re-trace per setting or drag a dynamic ``top_k`` sort into
every step).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# masked-out logit value for top-k filtering; finite (not -inf) so a
# pathological all-filtered row degrades to uniform instead of NaN
_FILTERED = -1e30


def sample_logits(logits: jax.Array, key: Optional[jax.Array] = None,
                  *, temperature: float = 0.0, top_k: int = 0) -> jax.Array:
    """(b, V) logits → (b,) int32 token ids.

    ``temperature == 0`` is greedy argmax (no key needed). Otherwise the
    categorical draw runs over ``logits / temperature``, optionally
    restricted to each row's ``top_k`` highest logits (``top_k == 0`` =
    full vocab). The softmax normalization happens inside
    ``jax.random.categorical`` via the Gumbel trick — no materialized
    probability vector."""
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        raise ValueError("temperature > 0 sampling requires a PRNG key")
    scaled = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, _FILTERED, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
