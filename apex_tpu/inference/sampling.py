"""Token sampling for the decode loop.

Greedy, temperature, top-k, and top-p (nucleus) sampling over the
last-position logits. ``temperature``/``top_k``/``top_p`` are STATIC
(python numbers fixed at engine construction): inside the jit'd
``decode_step`` they select the sampling program once — the sampled path
never branches at run time, which is part of the zero-recompile contract
(the alternative, traced sampling knobs, would either re-trace per
setting or drag a dynamic ``top_k`` sort into every step).

This is the STANDALONE sampler — the canonical, sort/cumsum-formulated
reference (every op shape-stable: a full descending sort and a cumsum
regardless of the knobs' values). The serving engines' hot path instead
runs :func:`apex_tpu.ops.fused_sample` — one fused kernel with
bisection-found thresholds — and ``tests/test_serving.py`` pins the two
formulations to the same kept set.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# masked-out logit value for top-k/top-p filtering; finite (not -inf) so
# a pathological all-filtered row degrades to uniform instead of NaN
_FILTERED = -1e30


def sample_logits(logits: jax.Array, key: Optional[jax.Array] = None,
                  *, temperature: float = 0.0, top_k: int = 0,
                  top_p: float = 1.0) -> jax.Array:
    """(b, V) logits → (b,) int32 token ids.

    ``temperature == 0`` is greedy argmax (no key needed). Otherwise the
    categorical draw runs over ``logits / temperature``, optionally
    restricted to each row's ``top_k`` highest logits (``top_k == 0`` =
    full vocab) and then to the NUCLEUS: the minimal highest-probability
    set whose softmax mass reaches ``top_p`` (``top_p == 1`` = full
    vocab; the token that crosses ``top_p`` is kept, ties at the cutoff
    value are all kept). Filters compose in the top-k → top-p order (the
    nucleus is computed over the already-top-k-restricted distribution).
    The softmax normalization happens inside ``jax.random.categorical``
    via the Gumbel trick — no materialized probability vector."""
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        raise ValueError("temperature > 0 sampling requires a PRNG key")
    scaled = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jax.lax.top_k(scaled, min(top_k, scaled.shape[-1]))[0][..., -1:]
        scaled = jnp.where(scaled < kth, _FILTERED, scaled)
    if top_p < 1.0:
        # shape-stable nucleus: full descending sort + cumsum, cutoff at
        # the first row position whose cumulative mass reaches top_p
        # (filtered entries sort last with ~0 probability, so top-k
        # composition is automatic)
        desc = -jnp.sort(-scaled, axis=-1)
        probs = jax.nn.softmax(desc, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        cut = jnp.argmax(csum >= top_p, axis=-1)
        cutoff = jnp.take_along_axis(desc, cut[..., None], axis=-1)
        scaled = jnp.where(scaled < cutoff, _FILTERED, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
