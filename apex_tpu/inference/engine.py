"""KV-cached autoregressive decode engine for the flagship GPT.

The serving path the training repo lacked: generating a token by re-running
the full forward over the whole prefix is O(s²) work per token; with a KV
cache each new token costs one token's projections plus ONE streaming pass
over the cache — the O(s) HBM-bound floor decode lives at ("LLM Inference
Acceleration via Efficient Operation Fusion", arXiv:2502.17728: the decode
hot path is memory-bound and won by removing staging traffic and per-token
dispatch, not FLOPs).

Design contract (what makes ``decode_step`` compile ONCE and stay compiled):

* **Pre-allocated, donated cache.** ``init_cache`` allocates
  ``(layers, batch, kv_heads, max_s, head_dim)`` k/v buffers up front —
  the attention-native layout :func:`apex_tpu.ops.decode_attention` reads
  directly. Every step updates them via ``lax.dynamic_update_slice`` at a
  *traced* position, so the avals never change; ``donate_argnums`` hands
  the buffers back to XLA so the update is in place — no per-token HBM
  realloc, no copy of the O(layers·batch·max_s) state.
* **Stable avals everywhere.** The step signature is
  ``(params, cache, tokens (b,), pos scalar, key)`` — every argument keeps
  one shape/dtype for the whole generation, so the jit cache holds exactly
  one executable (asserted by ``tests/test_inference.py`` via
  ``decode_step._cache_size()``).
* **Static sampling config.** temperature/top-k are fixed at engine
  construction (they select the sampling program, not data).

Prefill reuses the training forward (flash-attention blocks) over the whole
prompt at once and returns the populated cache — one compile per distinct
prompt length (pad prompts to a few bucket lengths to bound that).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.inference.sampling import sample_logits
from apex_tpu.models.gpt import GPTModel, shard_params_for_tp
from apex_tpu.monitor import spans as monitor_spans
from apex_tpu.monitor import trace as monitor_trace
from apex_tpu.ops import (decode_attention, fused_layer_norm, fused_verify,
                          fused_verify_tree)
from apex_tpu.ops.pallas.attention import NEG_INF
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.serving import tp as tp_serving


@dataclass
class SpecStats:
    """Host-side accounting of one speculative ``generate`` call.

    ``drafted`` counts PATH DEPTH per round (the chain's k; the tree's
    drafted depth), so ``acceptance_rate`` compares across chain and
    tree rounds; ``nodes`` counts total verify rows scored (== drafted
    for chains, branching x depth per tree round) — the denominator of
    draft-compute efficiency."""

    rounds: int = 0
    drafted: int = 0
    accepted: int = 0
    nodes: int = 0

    @property
    def acceptance_rate(self) -> float:
        """Accepted drafts / drafted tokens (0.0 before any round)."""
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def efficiency(self) -> float:
        """Emitted tokens (accepted + one bonus per round) per verify
        row scored — what adaptive (k, b) selection maximizes."""
        rows = self.nodes + self.rounds  # + the root row per round
        return (self.accepted + self.rounds) / rows if rows else 0.0


class DecodeEngine:
    """Batched greedy/sampling generation over a :class:`GPTModel`.

    ``engine = DecodeEngine(model)``;
    ``tokens = engine.generate(params, prompt, max_new_tokens)``.

    ``max_seq_len`` sizes the cache (default: the model's) and MUST be a
    multiple of 128 — the fused decode kernel streams the cache in
    128-column tiles, so any other length silently drops to the XLA
    fallback on TPU; that policy-by-accident was worth turning into an
    eager error. A cache may be ROUNDED UP past the model's position
    table (``max_seq_len=((n + 127) // 128) * 128``): the extra rows are
    tiling slack, and ``generate`` still refuses to step positions past
    the table itself. ``cache_dtype`` defaults to the model's param
    dtype; serve bf16 caches for 2x cache capacity at bf16-activation
    quality.
    """

    def __init__(self, model: GPTModel, *, max_seq_len: Optional[int] = None,
                 cache_dtype: Any = None, temperature: float = 0.0,
                 top_k: int = 0, plan=None):
        model.check_decode_supported()
        self.model = model
        c = self.config = model.config
        self.max_s = int(max_seq_len or c.max_seq_len)
        if self.max_s < 1 or self.max_s % 128:
            raise ValueError(
                f"max_seq_len ({self.max_s}) must be a positive multiple "
                f"of 128 (the fused decode kernel's cache-tiling "
                f"constraint) — round the cache up: DecodeEngine(model, "
                f"max_seq_len={((self.max_s + 127) // 128) * 128}); "
                f"generation is still capped by the model's position "
                f"table ({c.max_seq_len})")
        if self.max_s > ((c.max_seq_len + 127) // 128) * 128:
            raise ValueError(
                f"cache max_seq_len ({self.max_s}) exceeds the model's "
                f"position table ({c.max_seq_len}) by more than the "
                f"128-rounding slack")
        self.cache_dtype = cache_dtype or c.dtype
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        # tensor-parallel decode (ROADMAP tier 2c): plan.tp >= 2 shards
        # the cache's kv-head axis and the projections across chips.
        # DecodeEngine is GREEDY-only under tp: its sampled path is
        # jax.random.categorical, whose draws do not compose bitwise
        # across vocab shards (ServingEngine's fused Gumbel tail does)
        self.plan = plan
        self.tp = int(plan.tp) if plan is not None else 1
        self._mesh = None
        if self.tp > 1:
            if self.temperature > 0:
                raise ValueError(
                    f"temperature={self.temperature} with plan.tp="
                    f"{self.tp}: DecodeEngine's sampled path draws via "
                    f"jax.random.categorical, which does not compose "
                    f"across vocab shards — decode greedy "
                    f"(temperature=0.0) under tp, or sample through "
                    f"ServingEngine's psum-composed fused tail")
            tp_serving.validate_tp(
                plan, c, engine="DecodeEngine",
                temperature=self.temperature, top_k=self.top_k,
                has_rel_bias=getattr(model, "decode_rel_bias",
                                     None) is not None)
            self._mesh = tp_serving.tp_mesh(self.tp)
            P = jax.sharding.PartitionSpec
            kv, rep = P(None, None, "tp"), P()
            cache_spec = {"k": kv, "v": kv}
            self._cache_spec = cache_spec
            # replicated-activation shard bodies (overlap=False helpers:
            # batch and prompt lengths are not tp-divisible in general,
            # so the boundary collectives are plain psums here; the
            # ring-overlap contract is witnessed on the ServingEngine
            # programs). Logits reassemble the vocab row via output
            # sharding — never an all_gather inside the program.
            self._tp_prefill = mesh_lib.shard_map(
                self._prefill_body_tp, mesh=self._mesh,
                in_specs=(P("tp"), rep, rep),
                out_specs=(cache_spec, rep, P(None, "tp")))
            self._tp_decode = mesh_lib.shard_map(
                self._decode_step_body_tp, mesh=self._mesh,
                in_specs=(P("tp"), cache_spec, rep, rep, rep),
                out_specs=(cache_spec, rep, P(None, "tp")))
            self._tp_spec = mesh_lib.shard_map(
                self._spec_verify_body_tp, mesh=self._mesh,
                in_specs=(P("tp"), cache_spec, rep, rep, rep, rep),
                out_specs=(cache_spec, rep, rep))
        # one jitted executable each; decode additionally donates the cache
        # (argnums: params=0, cache=1, tokens=2, pos=3, key=4)
        self.prefill = jax.jit(self._prefill)
        self.decode_step = jax.jit(self._decode_step, donate_argnums=(1,))
        # the speculative round: k+1 tokens scored in one multi-token
        # step + the fused verify tail; avals depend only on the static
        # draft length k, so across rounds it compiles exactly once
        self.spec_verify_step = jax.jit(self._spec_verify_step,
                                        donate_argnums=(1,))
        # the TREE round: N+1 nodes scored in one forward under the
        # tree-attention mask + the fused tree-verify tail; avals depend
        # only on (N+1, depth+1) — both carried by operand SHAPES
        # (parents/anc and the levels iota), so the jit cache holds one
        # executable per (k, b) topology in use and nothing retraces
        # across rounds, streams, or acceptance patterns
        self.spec_tree_step = jax.jit(self._spec_tree_verify_step,
                                      donate_argnums=(1,))
        self.last_spec_stats: Optional[SpecStats] = None

    # --- cache ---------------------------------------------------------------

    def init_cache(self, batch: int):
        """Pre-allocated zeroed KV cache:
        ``{"k"/"v": (layers, batch, kv_heads, max_s, head_dim)}``."""
        c = self.config
        shape = (c.num_layers, batch, c.local_kv_heads, self.max_s,
                 c.head_dim)
        return {"k": jnp.zeros(shape, self.cache_dtype),
                "v": jnp.zeros(shape, self.cache_dtype)}

    def cache_bytes(self, batch: int) -> int:
        """HBM footprint of one cache (both k and v), for capacity math."""
        c = self.config
        itemsize = jnp.dtype(self.cache_dtype).itemsize
        return (2 * c.num_layers * batch * c.local_kv_heads * self.max_s
                * c.head_dim * itemsize)

    # --- prefill -------------------------------------------------------------

    def _sample(self, logits, key):
        return sample_logits(logits, key, temperature=self.temperature,
                             top_k=self.top_k)

    def _prefill(self, params, tokens, key):
        """Prompt (b, s) → (cache populated at [0, s), next token (b,),
        last-position logits (b, V)). The forward is the training block
        structure (flash attention over the full prompt) with each layer's
        k/v exposed — cache contents ARE the training forward's k/v."""
        with monitor_spans.span("decode_prefill"):
            if self.tp > 1:
                return self._tp_prefill(params, tokens, key)
            return self._prefill_body(params, tokens, key)

    def _prefill_body(self, params, tokens, key):
        model, c = self.model, self.config
        b, s = tokens.shape
        x = model.embedding(params["embedding"], tokens)
        x = x + params["pos_embedding"][:s]
        ks, vs = [], []
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            x, (k, v) = model.prefill_block(layer, x)
            ks.append(k)
            vs.append(v)
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x[:, -1:])[:, 0]
        cache = self.init_cache(b)
        # static-length write: s is a trace-time constant of this prompt
        cache = {
            "k": cache["k"].at[:, :, :, :s].set(
                jnp.stack(ks).astype(self.cache_dtype)),
            "v": cache["v"].at[:, :, :, :s].set(
                jnp.stack(vs).astype(self.cache_dtype)),
        }
        return cache, self._sample(logits, key), logits

    # --- decode --------------------------------------------------------------

    def _decode_step(self, params, cache, tokens, pos, key):
        """One generation step: run ``tokens`` (b,) — the tokens at
        position ``pos`` (scalar int32, count of cache rows already live)
        — through the stack against the cache, write their k/v at ``pos``,
        and sample position ``pos+1``'s tokens. Returns (cache, next
        tokens, logits). Avals are independent of ``pos``: compiled
        exactly once per (batch, cache shape)."""
        # trace-time step-anatomy span: every HLO of the decode step
        # carries the decode_step scope into device traces (the join key
        # `monitor report --anatomy` correlates on); no-op when
        # monitoring is off, and never touches the zero-recompile avals
        with monitor_spans.span("decode_step"):
            if self.tp > 1:
                return self._tp_decode(params, cache, tokens, pos, key)
            return self._decode_step_body(params, cache, tokens, pos, key)

    def _decode_step_body(self, params, cache, tokens, pos, key):
        model, c = self.model, self.config
        b = tokens.shape[0]
        pos = jnp.asarray(pos, jnp.int32)
        x = model.embedding(params["embedding"], tokens[:, None])
        x = x + jax.lax.dynamic_slice(
            params["pos_embedding"], (pos, 0), (1, c.hidden_size))[None]
        ck, cv = cache["k"], cache["v"]
        lengths = jnp.full((b,), pos + 1, jnp.int32)
        zero = jnp.int32(0)
        # T5-style relative bias at decode, for free: a model exposing
        # ``decode_rel_bias(params) -> BucketedBias`` (causal table) gets
        # it threaded into every block's fused decode attention — the
        # kernel recomputes the bias from the tiny table and the live
        # length, so the cache layout, avals, and the zero-recompile
        # contract are untouched. Models without the hook (stock GPT:
        # learned positions) pass None.
        rel_hook = getattr(model, "decode_rel_bias", None)
        rel_bias = None if rel_hook is None else rel_hook(params)
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            q, k_row, v_row = model.decode_qkv(layer, x)
            # in-place row write into the DONATED stacked buffers (layer
            # index static, position traced — one executable for all pos)
            ck = jax.lax.dynamic_update_slice(
                ck, k_row[None].astype(ck.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            cv = jax.lax.dynamic_update_slice(
                cv, v_row[None].astype(cv.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            x = model.decode_block(layer, x, q, ck[i], cv[i], lengths,
                                   rel_bias=rel_bias)
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x)[:, 0]
        return {"k": ck, "v": cv}, self._sample(logits, key), logits

    # --- tensor-parallel bodies (plan.tp >= 2) -------------------------------
    #
    # Per-shard twins run INSIDE shard_map: params arrive as
    # shard_params_for_tp slices, the cache's kv-head axis is this
    # shard's contiguous slice, activations stay replicated (batch and
    # prompt lengths aren't tp-divisible in general, so projections use
    # the plain dot+psum form), and the greedy argmax / verify tails
    # psum-compose so every shard emits identical tokens.

    def _prepare_params(self, params):
        """tp == 1: passthrough. Under tp: split the replicated tree
        into per-rank shards (leading ``(tp,)`` axis) committed to the
        mesh under ``P('tp')``."""
        if self.tp == 1:
            return params
        sharded = shard_params_for_tp(params, self.tp, self.config)
        sh = jax.sharding.NamedSharding(self._mesh,
                                        jax.sharding.PartitionSpec("tp"))
        return jax.tree.map(lambda a: jax.device_put(a, sh), sharded)

    def _prefill_body_tp(self, params, tokens, key):
        c = self.config
        axis, tp = tp_serving.TENSOR_AXIS, self.tp
        h_loc, hkv_loc = c.num_heads // tp, c.kv_heads // tp
        group, d = h_loc // hkv_loc, c.head_dim
        params = tp_serving.take_shard(params)
        b, s = tokens.shape
        emb = params["embedding"]["weight"]
        x = tp_serving.vocab_embed(emb, tokens, axis=axis)
        x = x + params["pos_embedding"][:s]
        scale = 1.0 / d ** 0.5
        ii = jnp.arange(s, dtype=jnp.int32)
        mask = ii[None, None, :, None] >= ii[None, None, None, :]
        ks, vs = [], []
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            y = tp_serving.column_parallel(
                h_in, layer["qkv"]["weight"], layer["qkv"].get("bias"),
                axis=axis, overlap=False)
            q = y[..., :h_loc * d].reshape(b, s, h_loc, d)
            k = y[..., h_loc * d:(h_loc + hkv_loc) * d] \
                .reshape(b, s, hkv_loc, d)
            v = y[..., (h_loc + hkv_loc) * d:].reshape(b, s, hkv_loc, d)
            kh = k.transpose(0, 2, 1, 3)  # (b, hkv_loc, s, d)
            vh = v.transpose(0, 2, 1, 3)
            ks.append(kh)
            vs.append(vh)
            qg = q.reshape(b, s, hkv_loc, group, d) \
                .transpose(0, 2, 3, 1, 4)  # (b, hkv_loc, group, s, d)
            sc = jnp.einsum("bhgqd,bhkd->bhgqk", qg,
                            kh.astype(qg.dtype),
                            preferred_element_type=jnp.float32) * scale
            sc = jnp.where(mask[:, None], sc, NEG_INF)
            p = jax.nn.softmax(sc, axis=-1)
            ctx = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(vh.dtype), vh)
            ctx = ctx.transpose(0, 3, 1, 2, 4).reshape(b, s, h_loc * d)
            x = x + tp_serving.row_parallel(
                ctx, layer["attn_out"]["weight"],
                layer["attn_out"].get("bias"), axis=axis, overlap=False)
            h2 = fused_layer_norm(x, layer["ln2_w"], layer["ln2_b"])
            h = tp_serving.column_parallel(
                h2, layer["mlp_up"]["weight"],
                layer["mlp_up"].get("bias"), axis=axis, overlap=False)
            h = jax.nn.gelu(h, approximate=True)
            x = x + tp_serving.row_parallel(
                h, layer["mlp_down"]["weight"],
                layer["mlp_down"].get("bias"), axis=axis, overlap=False)
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = jnp.dot(x[:, -1], emb.T)  # (b, V/tp)
        shape = (c.num_layers, b, hkv_loc, self.max_s, d)
        cache = {"k": jnp.zeros(shape, self.cache_dtype),
                 "v": jnp.zeros(shape, self.cache_dtype)}
        cache = {
            "k": cache["k"].at[:, :, :, :s].set(
                jnp.stack(ks).astype(self.cache_dtype)),
            "v": cache["v"].at[:, :, :, :s].set(
                jnp.stack(vs).astype(self.cache_dtype)),
        }
        tok = tp_serving.row_argmax_tp(logits, axis=axis)
        return cache, tok, logits

    def _decode_step_body_tp(self, params, cache, tokens, pos, key):
        c = self.config
        axis, tp = tp_serving.TENSOR_AXIS, self.tp
        h_loc, hkv_loc = c.num_heads // tp, c.kv_heads // tp
        d = c.head_dim
        params = tp_serving.take_shard(params)
        b = tokens.shape[0]
        pos = jnp.asarray(pos, jnp.int32)
        emb = params["embedding"]["weight"]
        x = tp_serving.vocab_embed(emb, tokens[:, None], axis=axis)
        x = x + jax.lax.dynamic_slice(
            params["pos_embedding"], (pos, 0), (1, c.hidden_size))[None]
        ck, cv = cache["k"], cache["v"]
        lengths = jnp.full((b,), pos + 1, jnp.int32)
        zero = jnp.int32(0)
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            y = tp_serving.column_parallel(
                h_in[:, 0], layer["qkv"]["weight"],
                layer["qkv"].get("bias"), axis=axis, overlap=False)
            q = y[:, :h_loc * d].reshape(b, h_loc, d)
            k_row = y[:, h_loc * d:(h_loc + hkv_loc) * d] \
                .reshape(b, hkv_loc, d)
            v_row = y[:, (h_loc + hkv_loc) * d:].reshape(b, hkv_loc, d)
            ck = jax.lax.dynamic_update_slice(
                ck, k_row[None, :, :, None].astype(ck.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            cv = jax.lax.dynamic_update_slice(
                cv, v_row[None, :, :, None].astype(cv.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            # the fused decode-attention kernel, untouched: this shard
            # owns a contiguous kv-head slice of the contiguous cache
            ctx = decode_attention(q, ck[i], cv[i], lengths)
            out = tp_serving.row_parallel(
                ctx.reshape(b, h_loc * d), layer["attn_out"]["weight"],
                layer["attn_out"].get("bias"), axis=axis, overlap=False)
            x = x + out[:, None]
            h2 = fused_layer_norm(x, layer["ln2_w"], layer["ln2_b"])
            h = tp_serving.column_parallel(
                h2[:, 0], layer["mlp_up"]["weight"],
                layer["mlp_up"].get("bias"), axis=axis, overlap=False)
            h = jax.nn.gelu(h, approximate=True)
            m = tp_serving.row_parallel(
                h, layer["mlp_down"]["weight"],
                layer["mlp_down"].get("bias"), axis=axis, overlap=False)
            x = x + m[:, None]
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = jnp.dot(x[:, 0], emb.T)  # (b, V/tp)
        tok = tp_serving.row_argmax_tp(logits, axis=axis)
        return {"k": ck, "v": cv}, tok, logits

    def _spec_verify_body_tp(self, params, cache, tokens, pos, drafted,
                             key):
        c = self.config
        axis, tp = tp_serving.TENSOR_AXIS, self.tp
        h_loc, hkv_loc = c.num_heads // tp, c.kv_heads // tp
        group, d = h_loc // hkv_loc, c.head_dim
        params = tp_serving.take_shard(params)
        b, K1 = tokens.shape
        pos = jnp.asarray(pos, jnp.int32)
        positions = pos + jnp.arange(K1, dtype=jnp.int32)
        emb = params["embedding"]["weight"]
        x = tp_serving.vocab_embed(emb, tokens, axis=axis)  # (1, K1, H)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(positions, ptab.shape[0] - 1),
                         axis=0)[None]
        ck, cv = cache["k"], cache["v"]
        scale = 1.0 / d ** 0.5
        js = jnp.arange(self.max_s, dtype=jnp.int32)
        mask = js[None, None, None, :] <= positions[None, None, :, None]
        zero = jnp.int32(0)
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            y = tp_serving.column_parallel(
                h_in, layer["qkv"]["weight"], layer["qkv"].get("bias"),
                axis=axis, overlap=False)
            q = y[..., :h_loc * d]
            k = y[..., h_loc * d:(h_loc + hkv_loc) * d] \
                .reshape(b, K1, hkv_loc, d)
            v = y[..., (h_loc + hkv_loc) * d:].reshape(b, K1, hkv_loc, d)
            ck = jax.lax.dynamic_update_slice(
                ck, k.transpose(0, 2, 1, 3)[None].astype(ck.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            cv = jax.lax.dynamic_update_slice(
                cv, v.transpose(0, 2, 1, 3)[None].astype(cv.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            k_all, v_all = ck[i][0], cv[i][0]  # (hkv_loc, max_s, d)
            qg = q[0].reshape(K1, hkv_loc, group, d).transpose(1, 2, 0, 3)
            s = jnp.einsum("hgcd,hsd->hgcs", qg, k_all.astype(qg.dtype),
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask[0], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("hgcs,hsd->hgcd", p.astype(v_all.dtype),
                             v_all)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(b, K1, h_loc * d)
            x = x + tp_serving.row_parallel(
                ctx, layer["attn_out"]["weight"],
                layer["attn_out"].get("bias"), axis=axis, overlap=False)
            h2 = fused_layer_norm(x, layer["ln2_w"], layer["ln2_b"])
            h = tp_serving.column_parallel(
                h2, layer["mlp_up"]["weight"],
                layer["mlp_up"].get("bias"), axis=axis, overlap=False)
            h = jax.nn.gelu(h, approximate=True)
            x = x + tp_serving.row_parallel(
                h, layer["mlp_down"]["weight"],
                layer["mlp_down"].get("bias"), axis=axis, overlap=False)
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = jnp.dot(x, emb.T)  # (1, K1, V/tp)
        a, nxt = tp_serving.verify_greedy_tp(logits, drafted, axis=axis)
        return {"k": ck, "v": cv}, a, nxt

    # --- speculative verification --------------------------------------------

    def _spec_verify_step(self, params, cache, tokens, pos, drafted, key):
        """One speculative round: score ``tokens`` (1, k+1) — the
        pending sampled token followed by the k drafted continuations —
        in ONE multi-token step at cache rows [pos, pos+k], then run the
        fused verify-and-sample tail. Returns ``(cache, accept_len (1,),
        next_token (1,))``. The cache holds all k+1 rows' k/v on return;
        rows past the accepted frontier are rejected-draft garbage that
        the NEXT round's length masking hides and its writes overwrite —
        length masking IS the rewind on a contiguous cache. Avals depend
        only on the static k: one executable across every round."""
        with monitor_spans.span("spec_verify"):
            if self.tp > 1:
                return self._tp_spec(params, cache, tokens, pos,
                                     drafted, key)
            return self._spec_verify_body(params, cache, tokens, pos,
                                          drafted, key)

    def _spec_verify_body(self, params, cache, tokens, pos, drafted, key):
        model, c = self.model, self.config
        b, K1 = tokens.shape
        d = c.head_dim
        h_kv, group = c.local_kv_heads, c.local_heads // c.local_kv_heads
        pos = jnp.asarray(pos, jnp.int32)
        positions = pos + jnp.arange(K1, dtype=jnp.int32)
        x = model.embedding(params["embedding"], tokens)  # (1, K1, H)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(positions, ptab.shape[0] - 1),
                         axis=0)[None]
        ck, cv = cache["k"], cache["v"]
        scale = 1.0 / d ** 0.5
        js = jnp.arange(self.max_s, dtype=jnp.int32)
        # prefix-causal per drafted row: row i sees keys [0, pos + i]
        mask = js[None, None, None, :] <= positions[None, None, :, None]
        zero = jnp.int32(0)
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            q, k, v = model._proj_qkv_bshd(layer, h_in)
            # one contiguous K1-row write at the traced frontier (the
            # multi-token sibling of the decode step's single-row write)
            ck = jax.lax.dynamic_update_slice(
                ck, k.transpose(0, 2, 1, 3)[None].astype(ck.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            cv = jax.lax.dynamic_update_slice(
                cv, v.transpose(0, 2, 1, 3)[None].astype(cv.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            # K1 queries × the full cache — the flash multi-token
            # scoring shape (the prefill-chunk attention at chunk=k+1)
            k_all, v_all = ck[i][0], cv[i][0]  # (h_kv, max_s, d)
            qg = q[0].reshape(K1, h_kv, group, d).transpose(1, 2, 0, 3)
            s = jnp.einsum("hgcd,hsd->hgcs", qg, k_all.astype(qg.dtype),
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask[0], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("hgcs,hsd->hgcd", p.astype(v_all.dtype),
                             v_all)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(1, K1, c.local_heads,
                                                    d)
            x = x + model._proj_attn_out(layer, ctx)
            x = x + model._mlp(layer, fused_layer_norm(
                x, layer["ln2_w"], layer["ln2_b"]))
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x)  # (1, K1, V)
        a, nxt = fused_verify(logits, drafted, key,
                              temperature=self.temperature,
                              top_k=self.top_k)
        return {"k": ck, "v": cv}, a, nxt

    def _spec_tree_verify_step(self, params, cache, tokens, pos, parents,
                               anc, levels, key):
        """One TREE speculative round: score ``tokens`` (1, N+1) — the
        pending token (the root) plus N drafted tree nodes — in ONE
        forward, each node attending the committed cache rows plus its
        own root path via the ``anc`` tree-attention mask, then run the
        fused tree-verify tail and commit the WINNING path's k/v into
        cache rows [pos, pos+accept_len]. Returns ``(cache, accept_len
        (1,), j_star (1,), next_token (1,))``. Unlike the chain step,
        sibling nodes share positions so nothing is cache-scattered
        before the verdict; only the accepted path lands, selected
        level-by-level inside the same program (``levels`` is a
        ``(depth+1,)`` iota whose SHAPE carries the static depth).
        Rows past the accepted frontier hold zeros that next round's
        length masking hides — length masking IS the rewind."""
        with monitor_spans.span("spec_verify"):
            return self._spec_tree_verify_body(params, cache, tokens,
                                               pos, parents, anc, levels,
                                               key)

    def _spec_tree_verify_body(self, params, cache, tokens, pos, parents,
                               anc, levels, key):
        model, c = self.model, self.config
        b, N1 = tokens.shape
        d = c.head_dim
        h_kv, group = c.local_kv_heads, c.local_heads // c.local_kv_heads
        pos = jnp.asarray(pos, jnp.int32)
        depth_vec = jnp.sum(anc.astype(jnp.int32), axis=-1) - 1  # (1, N1)
        positions = pos + depth_vec[0]  # (N1,) — siblings SHARE positions
        x = model.embedding(params["embedding"], tokens)  # (1, N1, H)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(positions, ptab.shape[0] - 1),
                         axis=0)[None]
        ck, cv = cache["k"], cache["v"]
        scale = 1.0 / d ** 0.5
        js = jnp.arange(self.max_s, dtype=jnp.int32)
        # committed rows only: the root's own k/v rides the TREE part
        # (index 0), not the cache, until the verdict commits it
        cache_mask = js[None, None, :] < pos  # (1, 1, max_s)
        tree_mask = anc[0] != 0  # (N1 queries, N1 nodes): the root path
        tks, tvs = [], []
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            q, k, v = model._proj_qkv_bshd(layer, h_in)  # (1, N1, h, d)
            tks.append(k)
            tvs.append(v)
            k_all, v_all = ck[i][0], cv[i][0]  # (h_kv, max_s, d)
            qg = q[0].reshape(N1, h_kv, group, d).transpose(1, 2, 0, 3)
            s_c = jnp.einsum("hgcd,hsd->hgcs", qg,
                             k_all.astype(qg.dtype),
                             preferred_element_type=jnp.float32) * scale
            s_c = jnp.where(cache_mask[None], s_c, NEG_INF)
            kt = k[0].transpose(1, 0, 2)  # (h_kv, N1, d)
            vt = v[0].transpose(1, 0, 2)
            s_t = jnp.einsum("hgcd,hnd->hgcn", qg, kt.astype(qg.dtype),
                             preferred_element_type=jnp.float32) * scale
            s_t = jnp.where(tree_mask[None, None], s_t, NEG_INF)
            # ONE softmax across cache + tree keys — exactly the
            # distribution the committed-path decode would compute
            p = jax.nn.softmax(jnp.concatenate([s_c, s_t], axis=-1),
                               axis=-1)
            p_c, p_t = p[..., :self.max_s], p[..., self.max_s:]
            ctx = jnp.einsum("hgcs,hsd->hgcd", p_c.astype(v_all.dtype),
                             v_all) \
                + jnp.einsum("hgcn,hnd->hgcd", p_t.astype(vt.dtype), vt)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(1, N1, c.local_heads,
                                                    d)
            x = x + model._proj_attn_out(layer, ctx)
            x = x + model._mlp(layer, fused_layer_norm(
                x, layer["ln2_w"], layer["ln2_b"]))
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x)  # (1, N1, V)
        a, j_star, nxt = fused_verify_tree(
            logits, tokens, parents, anc, key,
            temperature=self.temperature, top_k=self.top_k)
        # commit the winning path: level l of j_star's root path (root =
        # level 0 = the pending token) lands at cache row pos + l; levels
        # past accept_len select nothing and write zeros (masked rows)
        ii = jnp.arange(N1, dtype=jnp.int32)
        onpath = jnp.einsum(
            "si,sin->sn", (ii[None] == j_star[:, None]).astype(jnp.float32),
            anc.astype(jnp.float32))  # (1, N1)
        lvl = onpath[:, None, :] * (
            depth_vec[:, None, :] == levels[None, :, None]
        ).astype(jnp.float32)  # (1, depth+1, N1)
        zero = jnp.int32(0)
        for i in range(c.num_layers):
            sel_k = jnp.einsum("bln,bnhd->bhld", lvl.astype(tks[i].dtype),
                               tks[i])
            sel_v = jnp.einsum("bln,bnhd->bhld", lvl.astype(tvs[i].dtype),
                               tvs[i])
            ck = jax.lax.dynamic_update_slice(
                ck, sel_k[None].astype(ck.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
            cv = jax.lax.dynamic_update_slice(
                cv, sel_v[None].astype(cv.dtype),
                (jnp.int32(i), zero, zero, pos, zero))
        return {"k": ck, "v": cv}, a, j_star, nxt

    def _generate_spec_tree(self, params, prompt, max_new_tokens, key,
                            draft, adaptive):
        """The tree-speculative driver behind ``generate(draft=<tree
        drafter>)``: one batched forward scores the whole draft tree,
        the fused tree verify emits the deepest accepted root path + a
        bonus token, and :class:`~apex_tpu.spec.tree.DraftTree` walks
        the verdict back to host tokens. ``adaptive`` (an
        :class:`~apex_tpu.spec.tree.AdaptiveSpecController`) re-picks
        (depth, branching) per round from its static choice set — each
        choice is one pinned executable."""
        from apex_tpu.spec.drafter import validate_drafter
        from apex_tpu.spec.tree import draft_tree

        b, s = prompt.shape
        if b != 1:
            raise ValueError(
                f"draft= speculative generation runs batch 1 (accepted "
                f"lengths diverge across rows, and the contiguous cache "
                f"carries one scalar position); got batch {b} — split "
                f"the batch, or serve it through ServingEngine.serve("
                f"draft=...) which speculates per slot")
        if self.tp > 1:
            raise ValueError(
                "tree-speculative generation has no tensor-parallel "
                "body — decode tree drafts at tp=1, or use a chain "
                "drafter (which verifies through the tp twin)")
        if getattr(self.model, "decode_rel_bias", None) is not None:
            raise ValueError(
                "draft= speculative decoding cannot run a model with a "
                "decode relative-position bias (the spec verify step "
                "does not carry the bucketed bias) — generate with "
                "draft=None for this model")
        shapes = (adaptive.choices if adaptive is not None
                  else ((draft.depth, draft.branching),))
        depth_max = max(dd for dd, _ in shapes)
        validate_drafter(draft, self.config,
                         needed_rows=s + max_new_tokens + depth_max)
        if s + max_new_tokens + depth_max - 1 > self.max_s:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + "
                f"tree depth ({depth_max}) - 1 exceeds the cache "
                f"({self.max_s}): a tree round writes depth rows past "
                f"the live frontier — raise max_seq_len or lower the "
                f"drafter's depth")
        if s + max_new_tokens + depth_max - 1 > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + "
                f"tree depth ({depth_max}) - 1 steps past the model's "
                f"position table ({self.config.max_seq_len}); drafted "
                f"rows hold real positions too — lower the depth or the "
                f"request")
        cache, tok, _ = self.prefill(params, prompt,
                                     jax.random.fold_in(key, 0))
        stats = SpecStats()
        gen = [int(jnp.asarray(tok)[0])]
        context = [int(t) for t in jnp.asarray(prompt)[0]] + gen
        while len(gen) < max_new_tokens:
            depth, branching = (adaptive.choice(0) if adaptive is not None
                                else (draft.depth, draft.branching))
            tree = draft_tree(branching, depth)
            node_tokens = np.asarray(
                draft.propose_tree(0, context, shape=(depth, branching)),
                np.int32).reshape(-1)
            if node_tokens.shape != (tree.num_nodes,):
                raise ValueError(
                    f"drafter proposed {node_tokens.shape} node tokens; "
                    f"the ({branching}, {depth}) topology needs exactly "
                    f"{tree.num_nodes} (static shapes keep the verify "
                    f"program compiled once per topology)")
            parents, anc = tree.operands(1)
            pos = s + len(gen) - 1
            cache, a, j_star, nxt = self.spec_tree_step(
                params, cache,
                jnp.asarray([[gen[-1], *node_tokens]], jnp.int32),
                jnp.int32(pos), jnp.asarray(parents), jnp.asarray(anc),
                jnp.arange(depth + 1, dtype=jnp.int32),
                jax.random.fold_in(key, 1 + stats.rounds))
            a = int(jnp.asarray(a)[0])
            emitted = tree.path_tokens(node_tokens, a,
                                       int(jnp.asarray(j_star)[0]),
                                       int(jnp.asarray(nxt)[0]))
            gen.extend(emitted)
            context.extend(emitted)
            stats.rounds += 1
            stats.drafted += depth
            stats.accepted += a
            stats.nodes += tree.num_nodes
            if adaptive is not None:
                adaptive.note_round(0, a, depth)
        draft.release(0)
        if adaptive is not None:
            adaptive.release(0)
        self.last_spec_stats = stats
        return jnp.asarray([gen[:max_new_tokens]], jnp.int32)

    def _generate_spec(self, params, prompt, max_new_tokens, key, draft):
        """The speculative driver behind ``generate(draft=...)``."""
        from apex_tpu.spec.drafter import validate_drafter

        b, s = prompt.shape
        if b != 1:
            raise ValueError(
                f"draft= speculative generation runs batch 1 (accepted "
                f"lengths diverge across rows, and the contiguous cache "
                f"carries one scalar position); got batch {b} — split "
                f"the batch, or serve it through ServingEngine.serve("
                f"draft=...) which speculates per slot")
        if getattr(self.model, "decode_rel_bias", None) is not None:
            # the k+1-row spec scoring does not thread the bucketed
            # relative bias the plain decode step applies — verifying
            # biased baseline logits against unbiased spec logits would
            # silently break the token-identical contract
            raise ValueError(
                "draft= speculative decoding cannot run a model with a "
                "decode relative-position bias (the spec verify step "
                "does not carry the bucketed bias) — generate with "
                "draft=None for this model")
        K = validate_drafter(draft, self.config,
                             needed_rows=s + max_new_tokens
                             + getattr(draft, "k", 1))
        # the deepest row a round can touch: the last round starts at
        # most at pos = s + max_new - 2 and writes rows pos..pos+K
        if s + max_new_tokens + K - 1 > self.max_s:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + "
                f"draft.k ({K}) - 1 exceeds the cache ({self.max_s}): a "
                f"spec round writes k draft rows past the live frontier "
                f"— raise max_seq_len or lower draft.k")
        if s + max_new_tokens + K - 1 > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + "
                f"draft.k ({K}) - 1 steps past the model's position "
                f"table ({self.config.max_seq_len}); drafted rows hold "
                f"real positions too — lower draft.k or the request")
        cache, tok, _ = self.prefill(params, prompt,
                                     jax.random.fold_in(key, 0))
        stats = SpecStats()
        gen = [int(jnp.asarray(tok)[0])]
        context = [int(t) for t in jnp.asarray(prompt)[0]] + gen
        while len(gen) < max_new_tokens:
            drafted = np.asarray(
                draft.propose(0, context), np.int32).reshape(-1)
            if drafted.shape != (K,):
                raise ValueError(
                    f"drafter proposed {drafted.shape} tokens; the "
                    f"contract is exactly k={K} per round (static k "
                    f"keeps the verify program compiled once)")
            pos = s + len(gen) - 1
            cache, a, nxt = self.spec_verify_step(
                params, cache,
                jnp.asarray([[gen[-1], *drafted]], jnp.int32),
                jnp.int32(pos), jnp.asarray(drafted[None]),
                jax.random.fold_in(key, 1 + stats.rounds))
            a = int(jnp.asarray(a)[0])
            emitted = [int(t) for t in drafted[:a]] \
                + [int(jnp.asarray(nxt)[0])]
            gen.extend(emitted)
            context.extend(emitted)
            stats.rounds += 1
            stats.drafted += K
            stats.accepted += a
            stats.nodes += K
        draft.release(0)
        self.last_spec_stats = stats
        return jnp.asarray([gen[:max_new_tokens]], jnp.int32)

    # --- generation loop -----------------------------------------------------

    def generate(self, params, prompt, max_new_tokens: int,
                 key: Optional[jax.Array] = None,
                 draft=None, adaptive=None) -> jax.Array:
        """Greedy/sampled continuation: prompt (b, s) int32 → generated
        tokens (b, max_new_tokens). Python-loop driver over the jit'd
        steps; the loop body re-binds the donated cache each step.

        ``draft`` attaches a :class:`~apex_tpu.spec.drafter.Drafter`
        for speculative decoding (batch 1): each round the drafter
        proposes k tokens, ONE ``spec_verify_step`` scores all k+1
        positions and the fused verify tail accepts the longest valid
        prefix — greedy output token-identical to ``draft=None``, 1 to
        k+1 tokens per target dispatch, acceptance accounted in
        :attr:`last_spec_stats`. A TREE-capable drafter (one exposing
        ``propose_tree`` + ``depth``/``branching``, e.g.
        :class:`~apex_tpu.spec.tree.NGramTreeDrafter`) instead drafts a
        branching tree per round, scored in one forward and verified by
        the fused tree tail — same token-identical contract, 1 to
        depth+1 tokens per dispatch. ``adaptive`` (tree drafters only)
        attaches an :class:`~apex_tpu.spec.tree.AdaptiveSpecController`
        that re-picks (depth, branching) per round from its static
        choice set."""
        b, s = prompt.shape
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens} (the "
                f"prefill itself samples the first token)")
        if s + max_new_tokens > self.max_s:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the cache ({self.max_s})")
        # a 128-rounded cache may outsize the position table; positions
        # actually stepped may not (the last DECODED position is
        # s + max_new_tokens - 2: the final sampled token never re-enters)
        if s + max_new_tokens - 1 > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) steps "
                f"past the model's position table "
                f"({self.config.max_seq_len}); the cache's 128-rounding "
                f"slack holds no positions")
        if self.temperature > 0 and key is None:
            raise ValueError("temperature > 0 generation requires a key")
        if key is None:  # greedy: the key operand is ignored but keeps the
            key = jax.random.PRNGKey(0)  # step signature (and avals) fixed
        # under tp the steps consume the sharded (tp,)-leading tree,
        # committed to the mesh once per generate() call
        params = self._prepare_params(params)
        # one trace id per generate() call: every span the loop emits
        # (decode_prefill, decode_step, spec_verify) joins to this call
        # in a merged timeline. An already-ambient id (a caller's serve/
        # step context) is reused rather than shadowed.
        tid = (monitor_trace.current_trace_id()
               or monitor_trace.new_trace_id("gen"))
        with monitor_trace.trace_context(tid):
            if draft is not None:
                from apex_tpu.spec.tree import is_tree_drafter
                if is_tree_drafter(draft):
                    return self._generate_spec_tree(
                        params, prompt, max_new_tokens, key, draft,
                        adaptive)
                if adaptive is not None:
                    raise ValueError(
                        "adaptive= (k, b) selection needs a tree-capable "
                        "drafter (propose_tree + depth/branching); this "
                        "drafter only proposes chains — use "
                        "NGramTreeDrafter/PagedModelDrafter, or drop "
                        "adaptive=")
                return self._generate_spec(params, prompt,
                                           max_new_tokens, key, draft)
            if adaptive is not None:
                raise ValueError(
                    "adaptive= requires draft= (there is no draft shape "
                    "to adapt without a drafter)")
            cache, tok, _ = self.prefill(params, prompt,
                                         jax.random.fold_in(key, 0))
            out = [tok]
            for t in range(1, max_new_tokens):
                cache, tok, _ = self.decode_step(
                    params, cache, tok, jnp.int32(s + t - 1),
                    jax.random.fold_in(key, t))
                out.append(tok)
            return jnp.stack(out, axis=1)


def jit_encoder(model, *, with_pooler: bool = True):
    """BERT-style encoder serving: the trivial reuse case — encoders have
    no autoregressive structure, so "inference engine" is just the
    training forward jit'd with stable (padded-batch) avals. Returns
    ``encode(params, tokens, token_types=None, pad_mask=None)`` →
    (hidden (b, s, H), pooled (b, H) or None). Pad every request batch to
    fixed (b, s) buckets and pass ``pad_mask`` so one executable serves
    all traffic."""
    @functools.partial(jax.jit, static_argnames=("pool",))
    def _encode(params, tokens, token_types, pad_mask, pool):
        hidden = model.hidden_states(params, tokens, token_types=token_types,
                                     pad_mask=pad_mask)
        pooled = model.pooled(params, hidden) if pool else None
        return hidden, pooled

    def encode(params, tokens, token_types=None, pad_mask=None
               ) -> Tuple[jax.Array, Optional[jax.Array]]:
        return _encode(params, tokens, token_types, pad_mask, with_pooler)

    return encode
