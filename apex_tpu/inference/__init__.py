"""Inference: KV-cached autoregressive decode + encoder serving.

The serving tier the training-only reference never had (ROADMAP north
star: "serves heavy traffic from millions of users"). Three pieces:

* :class:`~apex_tpu.inference.engine.DecodeEngine` — batched generation
  for the flagship GPT: pre-allocated donated KV cache in the
  attention-native ``(layers, batch, kv_heads, max_s, head_dim)`` layout,
  jit'd ``prefill`` (reuses the flash-attention training forward) and a
  ``decode_step`` that compiles ONCE (stable avals, in-place
  ``dynamic_update_slice`` cache writes) — greedy, temperature, and
  top-k sampling;
* :func:`~apex_tpu.inference.engine.jit_encoder` — BERT-style encoder
  serving (stable-aval jit of the training forward; encoders need no
  cache);
* :func:`~apex_tpu.inference.sampling.sample_logits` — the sampling
  primitive.

``DecodeEngine.generate(..., draft=...)`` speculates: a
:class:`~apex_tpu.spec.drafter.Drafter` proposes a static k tokens per
round, one ``spec_verify_step`` scores all k+1 positions, and the fused
verify tail (:func:`apex_tpu.ops.fused_verify`) accepts the longest
valid prefix — greedy output token-identical to ``draft=None``, with
:class:`~apex_tpu.inference.engine.SpecStats` accounting acceptance
(``bench.py --spec`` measures the speedup).

The fused decode-attention op lives in
:func:`apex_tpu.ops.decode_attention` (Pallas kernel + XLA fallback);
the cached model math in :class:`apex_tpu.models.GPTModel`'s
``prefill_block``/``decode_qkv``/``decode_block`` branch. Serving
throughput is measured by ``python bench.py --decode`` (see
``docs/api/inference.md`` for the cache-layout and HBM-bound analysis).

This engine decodes ONE fixed batch in lockstep; serving mixed traffic
— requests of different lengths arriving at different times — lives one
layer up in :mod:`apex_tpu.serving` (continuous batching over a paged
block-pool cache, chunked prefill, fused sampling tail), which reuses
this module's decode math and sampling primitives.
"""

from apex_tpu.inference.engine import (  # noqa: F401
    DecodeEngine,
    SpecStats,
    jit_encoder,
)
from apex_tpu.inference.sampling import sample_logits  # noqa: F401
