"""Compatibility shim: ``apex_tpu.checkpoint`` grew into the
:mod:`apex_tpu.ckpt` subsystem (ISSUE 14).

Everything the seed module exported — ``TrainState``,
``save_checkpoint``/``restore_checkpoint``, ``CheckpointManager``,
``AutoResume``/``get_autoresume``, the amp state-dict parity helpers —
still imports from here unchanged (now orbax-OPTIONAL: the pure-numpy
npz fallback in :mod:`apex_tpu.ckpt.pytree_io` takes over when orbax is
absent). The dp-sharded elastic ZeRO format, the async off-step saver,
:class:`~apex_tpu.ckpt.manager.ZeroCheckpointManager` and the serving
hot-swap loader live in the package; import those from
``apex_tpu.ckpt`` directly.
"""

from apex_tpu.ckpt import (  # noqa: F401
    AsyncZeroSaver,
    AutoResume,
    CheckpointManager,
    Manifest,
    RestoredZero,
    SimulatedCrash,
    TrainState,
    ZeroCheckpointManager,
    amp_load_state_dict,
    amp_state_dict,
    get_autoresume,
    load_zero_state,
    restore_checkpoint,
    restore_params,
    restore_zero_shard,
    restore_zero_sharded,
    save_checkpoint,
    save_zero_sharded,
)
