"""Checkpoint / resume.

Re-design of the reference's checkpoint surface (SURVEY.md §5): the
reference persists amp's per-loss scaler state (``amp.state_dict()``
``frontend.py:361-400``), fp32 master weights regardless of cast
(``O2StateDictHook`` ``_initialize.py:133-143``), and
``FP16_Optimizer.state_dict`` (scaler + masters,
``fp16_optimizer.py:209-270``), documenting a bitwise-accurate resume recipe
(``README.md:60-100``).

Here one ``TrainState`` pytree holds (master params, optimizer state, loss
scaler state, step) and round-trips through orbax — saving the *fp32
masters* (like the O2 hook) so resume is bitwise regardless of the compute
dtype. ``save``/``restore`` are synchronous; pass an
``orbax.checkpoint.CheckpointManager`` for async/rotation policies.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Everything a bitwise resume needs (cf. README.md:60-100 recipe)."""

    step: jax.Array
    params: PyTree              # fp32 masters (O2StateDictHook semantics)
    opt_state: PyTree
    scaler_state: Optional[PyTree] = None
    extra: Optional[PyTree] = None  # e.g. BN running stats


def save_checkpoint(path: str, state: TrainState) -> None:
    if not _HAS_ORBAX:
        raise RuntimeError("orbax is unavailable in this environment")
    ckpt = ocp.StandardCheckpointer()
    ckpt.save(path, state)
    ckpt.wait_until_finished()


def restore_checkpoint(path: str, template: TrainState) -> TrainState:
    """Restore into the shapes/dtypes (and shardings) of ``template``."""
    if not _HAS_ORBAX:
        raise RuntimeError("orbax is unavailable in this environment")
    ckpt = ocp.StandardCheckpointer()
    return ckpt.restore(path, template)


# --- amp state-dict parity (frontend.py:361-400) ------------------------------

def amp_state_dict(scaler_states) -> dict:
    """``amp.state_dict()``: {'loss_scaler0': {...}, ...} per loss."""
    from apex_tpu.amp.scaler import state_dict as scaler_sd

    if not isinstance(scaler_states, (list, tuple)):
        scaler_states = [scaler_states]
    return {f"loss_scaler{i}": scaler_sd(s) for i, s in enumerate(scaler_states)}


def amp_load_state_dict(sd: dict, scaler_states):
    """``amp.load_state_dict()`` — loads each payload into the matching
    scaler state, returning the new states in order."""
    from apex_tpu.amp.scaler import load_state_dict as scaler_ld

    if not isinstance(scaler_states, (list, tuple)):
        scaler_states = [scaler_states]
    return [
        scaler_ld(s, sd[f"loss_scaler{i}"]) for i, s in enumerate(scaler_states)
    ]
