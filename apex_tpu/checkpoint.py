"""Checkpoint / resume.

Re-design of the reference's checkpoint surface (SURVEY.md §5): the
reference persists amp's per-loss scaler state (``amp.state_dict()``
``frontend.py:361-400``), fp32 master weights regardless of cast
(``O2StateDictHook`` ``_initialize.py:133-143``), and
``FP16_Optimizer.state_dict`` (scaler + masters,
``fp16_optimizer.py:209-270``), documenting a bitwise-accurate resume recipe
(``README.md:60-100``).

Here one ``TrainState`` pytree holds (master params, optimizer state, loss
scaler state, step) and round-trips through orbax — saving the *fp32
masters* (like the O2 hook) so resume is bitwise regardless of the compute
dtype. ``save``/``restore`` are synchronous; :class:`CheckpointManager`
below adds async saves and ``max_to_keep`` rotation, and
:class:`AutoResume` the save-on-preemption protocol.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Everything a bitwise resume needs (cf. README.md:60-100 recipe)."""

    step: jax.Array
    params: PyTree              # fp32 masters (O2StateDictHook semantics)
    opt_state: PyTree
    scaler_state: Optional[PyTree] = None
    extra: Optional[PyTree] = None  # e.g. BN running stats


def save_checkpoint(path: str, state: TrainState) -> None:
    if not _HAS_ORBAX:
        raise RuntimeError("orbax is unavailable in this environment")
    ckpt = ocp.StandardCheckpointer()
    ckpt.save(path, state)
    ckpt.wait_until_finished()


def restore_checkpoint(path: str, template: TrainState) -> TrainState:
    """Restore into the shapes/dtypes (and shardings) of ``template``."""
    if not _HAS_ORBAX:
        raise RuntimeError("orbax is unavailable in this environment")
    ckpt = ocp.StandardCheckpointer()
    return ckpt.restore(path, template)


class CheckpointManager:
    """Rotating, optionally-async checkpoints over :class:`TrainState` —
    beyond the reference's library-level state dicts (its trainers save
    synchronously with ``torch.save``): ``save`` returns once the on-device
    state is snapshotted and the write overlaps subsequent train steps;
    ``max_to_keep`` rotates old steps out. Thin policy layer over
    ``orbax.checkpoint.CheckpointManager`` so :class:`AutoResume` and the
    bitwise-resume guarantees of :func:`save_checkpoint` carry over.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True, save_interval_steps: int = 1):
        if not _HAS_ORBAX:
            raise RuntimeError("orbax is unavailable in this environment")
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: TrainState) -> bool:
        """Returns False when skipped by ``save_interval_steps``."""
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> TrainState:
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        return self._mgr.restore(step, args=ocp.args.StandardRestore(template))

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --- auto-resume / preemption (pipeline_parallel/utils.py:142-144) ------------

class AutoResume:
    """Save-on-preemption protocol. The reference carries an ADLR auto-resume
    stub (``get_autoresume`` ``apex/transformer/pipeline_parallel/utils.py:142-144``
    and the commented termination check ``:286-300``) that defers to an
    external cluster library; on Cloud TPU the termination signal is a plain
    SIGTERM delivered ahead of preemption, so the guard is self-contained:
    install signal handlers, poll ``termination_requested()`` from the train
    loop, and ``check_and_save`` writes the TrainState before exit.

    Handlers chain to any previously-installed handler and are restored by
    ``uninstall()``.
    """

    def __init__(self, signals=None):
        import signal as _signal

        self._signal = _signal
        self._requested = False
        self._prev = {}
        for s in signals if signals is not None else (_signal.SIGTERM,):
            try:
                self._prev[s] = _signal.signal(s, self._handler)
            except ValueError:
                # signal.signal only works on the main thread; degrade to the
                # cooperative protocol (request_termination still works)
                pass

    def _handler(self, signum, frame):
        self._requested = True
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def request_termination(self) -> None:
        """Mark termination as requested (tests / cooperative shutdown)."""
        self._requested = True

    def termination_requested(self) -> bool:
        return self._requested

    def check_and_save(self, path: str, state: TrainState) -> bool:
        """If termination was requested, checkpoint ``state`` to ``path`` and
        return True (caller should break its train loop). The analog of the
        reference's ``check_adlr_autoresume_termination``.

        On multi-host meshes the decision is agreed across processes first
        (a signal can land between two hosts' polls; an unagreed flag would
        have one host enter the collective orbax save while the others run
        ahead — the reason Megatron all-reduces its termination flag). All
        processes therefore return the same value and enter the save
        together."""
        if not self._agreed_termination():
            return False
        save_checkpoint(path, state)
        return True

    def _agreed_termination(self) -> bool:
        if jax.process_count() == 1:
            return self._requested
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            jnp.asarray(self._requested, jnp.int32))
        agreed = bool(np.max(np.asarray(flags)))
        if agreed:
            self._requested = True  # adopt the peer's signal
        return agreed

    def uninstall(self) -> None:
        global _AUTORESUME
        for s, prev in self._prev.items():
            self._signal.signal(s, prev)
        self._prev.clear()
        if _AUTORESUME is self:
            # never leave the singleton pointing at a dead (handler-less)
            # guard — the next get_autoresume() installs a fresh one
            _AUTORESUME = None


_AUTORESUME: Optional[AutoResume] = None


def get_autoresume() -> AutoResume:
    """Process-wide ``AutoResume`` (reference spelling:
    ``pipeline_parallel/utils.py:142-144``), installed on first use."""
    global _AUTORESUME
    if _AUTORESUME is None:
        _AUTORESUME = AutoResume()
    return _AUTORESUME


# --- amp state-dict parity (frontend.py:361-400) ------------------------------

def amp_state_dict(scaler_states) -> dict:
    """``amp.state_dict()``: {'loss_scaler0': {...}, ...} per loss."""
    from apex_tpu.amp.scaler import state_dict as scaler_sd

    if not isinstance(scaler_states, (list, tuple)):
        scaler_states = [scaler_states]
    return {f"loss_scaler{i}": scaler_sd(s) for i, s in enumerate(scaler_states)}


def amp_load_state_dict(sd: dict, scaler_states):
    """``amp.load_state_dict()`` — loads each payload into the matching
    scaler state, returning the new states in order."""
    from apex_tpu.amp.scaler import load_state_dict as scaler_ld

    if not isinstance(scaler_states, (list, tuple)):
        scaler_states = [scaler_states]
    return [
        scaler_ld(s, sd[f"loss_scaler{i}"]) for i, s in enumerate(scaler_states)
    ]
