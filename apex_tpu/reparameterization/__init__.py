"""Weight reparameterization (weight norm).

Re-design of ``apex.reparameterization`` (``apex/reparameterization/__init__.py``,
``weight_norm.py`` — deprecated in the reference but part of its surface).
The reference installs forward-pre hooks rewriting ``weight`` from (g, v);
functionally that is a parameterization pair: ``decompose`` splits a weight
into (g, v), ``compose`` rebuilds ``w = g * v / ||v||`` — applied to any
pytree leaf selection before the forward.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

PyTree = Any


def weight_norm_decompose(w: jax.Array, dim: int = 0) -> Tuple[jax.Array, jax.Array]:
    """w → (g, v) with g the per-slice norm along every axis but ``dim``
    (``WeightNorm.compute_weight`` inverse)."""
    axes = tuple(i for i in range(w.ndim) if i != dim)
    g = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))
    return g, w


def weight_norm_compose(g: jax.Array, v: jax.Array, dim: int = 0, eps: float = 1e-12) -> jax.Array:
    """(g, v) → w = g · v/||v|| (``weight_norm.py`` compute_weight)."""
    axes = tuple(i for i in range(v.ndim) if i != dim)
    norm = jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))
    return g * v / jnp.maximum(norm, eps)


def apply_weight_norm(params: PyTree, select: Callable[[str], bool] = None,
                      dim: int = 0) -> PyTree:
    """Split selected weights into {'g','v'} sub-dicts
    (``apply_weight_norm``; default: every leaf named 'weight')."""
    select = select or (lambda name: name.endswith("weight"))

    def walk(path, x):
        name = "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        if select(name) and x.ndim >= 2:
            g, v = weight_norm_decompose(x, dim)
            return {"g": g, "v": v}
        return x

    return jax.tree_util.tree_map_with_path(walk, params)


def remove_weight_norm(params: PyTree, dim: int = 0) -> PyTree:
    """Recompose {'g','v'} sub-dicts into plain weights
    (``remove_weight_norm``)."""
    def walk(x):
        if isinstance(x, dict) and set(x.keys()) == {"g", "v"}:
            return weight_norm_compose(x["g"], x["v"], dim)
        return x

    return jax.tree.map(walk, params,
                        is_leaf=lambda x: isinstance(x, dict) and set(x.keys()) == {"g", "v"})
