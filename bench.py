"""Flagship benchmark: GPT training-step throughput + MFU on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

The measured config is a GPT-medium-class decoder (hidden 1024 x 12 layers,
seq 1024, batch 20, bf16 compute) doing a full train step (loss + grad +
FusedAdam update). ``vs_baseline`` compares the framework path (flash
attention with recompute-in-backward, fused norm/softmax kernel family,
fused optimizer) against the same model written the stock-JAX way: naive
attention (materialized scores, jnp softmax, probs saved by autodiff) and
unfused optax adam — the TPU analog of the reference's "apex vs stock
PyTorch" pitch (the reference publishes no numbers of its own, SURVEY.md
§6). ``mfu`` uses the PaLM-style analytic model-FLOPs count
(6N + 12*L*S*H per token) against the chip's peak bf16 FLOP/s — the table
shared with ``apex_tpu.monitor.report``, so the report CLI derives the
same MFU from the same convention.

With ``APEX_TPU_MONITOR=<path>`` the bench additionally streams monitor
telemetry (a ``meta`` record + one ``step`` record per timed fused pass,
emitted AFTER each pass's clock stops) and ``python -m apex_tpu.monitor
report <path>`` reproduces the tokens/s headline from them. The printed
result object is schema-validated before printing (no nan can ship
inside a success artifact).

``python bench.py --decode`` runs the SERVING leg instead
(:func:`decode_main`): KV-cached decode tokens/s/chip, prefill latency,
and the ratio against the naive recompute-the-prefix baseline, emitted
as one ``decode`` monitor record (explicit ``SKIP(reason)`` off-TPU).

``python bench.py --serve`` runs the CONTINUOUS-BATCHING serving leg
(:func:`serve_main`): a SEEDED offered-load sweep (Poisson arrivals,
mixed lengths, shared system prompts, a pool sized below worst case)
through the paged ``apex_tpu.serving.ServingEngine`` — copy-on-write
prefix caching, optimistic admission + evict-and-recompute preemption,
SLO-aware dispatch — measuring p50/p99 per-token latency, TTFT split by
prefix hit vs miss, tokens/s under churn, occupancy, preemption and
recompute counts — as one ``serve`` monitor record with greedy-parity
(no-churn AND across-the-sweep ``churn_parity`` including evicted and
prefix-hit requests) and jit-cache-pinned witnesses vs the
single-request engine (explicit ``SKIP(reason)`` off-TPU).
Request-level telemetry rides along: streaming-histogram quantiles,
per-request ``serve_event`` lifecycle records (now incl. the ``evict``
trail), periodic ``serve_window`` SLO records, and the
``serve_anomaly`` section (stragglers, queue buildup, SLO burn, pool
leaks — refcount-aware: a warm prefix cache is not a leak).

``python bench.py --longseq-bias`` runs the long-sequence relative-bias
leg (:func:`longseq_bias_main`): in-kernel BUCKETED bias vs the
materialized (h, s, s) operand — tokens/s + HBM high-water, one
``longseq_bias`` monitor record (same SKIP semantics).

``python bench.py --tp-overlap`` runs the tensor-parallel overlap leg
(:func:`tp_overlap_main`): the ring-overlapped boundary collectives
(``GPTConfig(tp_overlap=True)`` → ``ops.collective_matmul``) vs the
blocking oracle, fwd+bwd tokens/s at tp >= 2 — one ``tp_overlap``
monitor record (``OK`` only on real multichip TPU; off-TPU the leg runs
at smoke scale on the virtual 8-device CPU mesh and the record is an
explicit ``SKIP(reason)``).

``python bench.py --pipeline`` runs the pipeline-schedule leg
(:func:`pipeline_main`): the zero-bubble split-backward schedule
(``GPTConfig(pp_schedule="zb")``) vs the autodiff 1f1b baseline through
``GPTPipeline`` at pp >= 2 — tokens/s for both, bubble % measured by
``step_anatomy`` on TPU and from the trace-time unit-cost geometry
everywhere, and a recompile-free witness across schedule-geometry
reuse — as one ``pipeline`` monitor record (same SKIP semantics).

``python bench.py --ckpt`` runs the elastic-checkpoint leg
(:func:`ckpt_main`): a ZeRO-sharded GPT train loop under
``apex_tpu.ckpt.ZeroCheckpointManager`` async saves — clean vs saving
step time (``save_overhead_pct``, the series ``tools/bench_history.py``
gates lower-is-better), snapshot/write/commit split, plus the bitwise
same-dp and elastic dp-resize resume witnesses measured in-process —
as one ``ckpt`` monitor record (same SKIP semantics off-TPU).

``python bench.py --spec`` runs the speculative-decoding +
quantized-KV leg (:func:`spec_main`): greedy generation with an n-gram
drafter vs the plain decode loop — tokens/s/request at batch 1 AND
under scheduler churn (``ServingEngine.serve(draft=...)``), the
acceptance rate, the greedy/churn parity witnesses, and the int8 KV
pool's teacher-forced logit error vs the float oracle — as one CLOSED
``spec`` monitor record (``tools/bench_history.py`` gates
``spec_tokens_per_s_request`` and the acceptance-rate series
higher-is-better; same SKIP semantics off-TPU). ``--spec --tree`` adds
the tree-speculation leg (:func:`_spec_tree_leg`): fused tree verify at
batch 1 and under churn with the drafter's KV in the SHARED paged pool,
peak drafter pool blocks, and the adaptive-vs-fixed (depth, branching)
witness on a recorded bimodal acceptance trace — the ``tree_spec_*``
series gate the same way.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import jax.random as jr

# the spec-peak table lives in apex_tpu.monitor.report — one table shared
# by this artifact and `python -m apex_tpu.monitor report`, so "mfu" means
# the same thing everywhere
from apex_tpu import monitor
from apex_tpu.monitor import trace as monitor_trace


def model_flops_per_token(cfg, seq):
    """PaLM-convention train-step FLOPs/token: 6*N_matmul + 12*L*S*H.

    N_matmul = per-layer matmul params (qkv 3H^2 + out H^2 + up 4H^2 +
    down 4H^2 = 12H^2) * L + tied unembedding V*H. Embedding lookup is a
    gather (0 FLOPs); LN/bias terms are negligible.
    """
    H, L, V = cfg["hidden_size"], cfg["num_layers"], cfg["vocab_size"]
    n_matmul = 12 * L * H * H + V * H
    return 6 * n_matmul + 12 * L * seq * H


def build(impl: str, cfg_kwargs, donate: bool):
    import optax

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import fused_adam

    if impl == "baseline":
        # the stock-JAX formulation: naive attention and whole-block
        # jax.checkpoint (remat stays ON here — the naive path's saved probs
        # blow 16G HBM by layer 3 without it; the framework path runs
        # un-rematted, which is itself framework value: the flash kernel's
        # O(s) residuals and the CE's recompute-from-lse backward are what
        # make that fit)
        cfg_kwargs = dict(cfg_kwargs, attention_impl="naive", remat=True,
                          remat_policy="full")
    cfg = GPTConfig(**cfg_kwargs)
    model = GPTModel(cfg)
    params = model.init(jr.PRNGKey(0))
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    if impl == "fused":
        opt = fused_adam(learning_rate=1e-4)
    else:
        opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss_fn)(params, tokens, targets)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jit_kwargs = {"donate_argnums": (0, 1)} if donate else {}
    return jax.jit(train_step, **jit_kwargs), params, opt_state


def timeit(step, params, opt_state, tokens, targets, iters, passes=3,
           return_passes=False, monitor_tokens=None):
    """Min over ``passes`` timed loops (min-of-3, VERDICT r4 next #7) —
    the remote tunnel adds transient stalls, and min-of-N is applied to
    BOTH impls so vs_baseline stays symmetric. ``return_passes``
    additionally returns the raw per-pass times so the shipped artifact
    carries its own noise bar (spread = (max-min)/min across passes; a
    single tunnel stall inflates max but never min). Donated buffers
    chain through the pass loop, so one call is safe under donation; do
    NOT reuse the caller's params/opt_state after it.

    ``monitor_tokens`` (tokens per iteration) additionally emits one
    monitor ``step`` record per timed pass — AFTER the pass's clock stops,
    so telemetry adds zero time inside the measured window (the <1%
    monitoring-overhead budget is enforced by construction)."""
    params, opt_state, loss = step(params, opt_state, tokens, targets)  # compile+warm
    float(loss)  # host fetch: the only reliable device sync over the tunnel
    times = []
    last_loss = None
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, tokens, targets)
        last_loss = float(loss)  # forces completion of the whole dependent chain
        times.append((time.perf_counter() - t0) / iters)
        if monitor_tokens is not None and monitor.enabled():
            monitor.begin_step()
            monitor.end_step(dur_s=times[-1], tokens=monitor_tokens,
                             loss=last_loss, iters=iters)
    best = min(times)
    if return_passes:
        return best, times
    return best


def decode_main():
    """``python bench.py --decode`` — the serving leg: KV-cached decode
    tokens/s/chip + prefill latency through ``apex_tpu.inference``,
    measured against the naive recompute-the-prefix formulation (the
    O(s²)-per-token path a repo without a KV cache is stuck with).

    Emits ONE ``decode`` record through the monitor schema (and onto the
    ``APEX_TPU_MONITOR`` stream when enabled) and prints it as one JSON
    line. On TPU the record is ``status: "OK"`` with the naive baseline
    and the cached/naive ratio; off-TPU it is an explicit
    ``status: "SKIP"`` with a reason — the smoke-scale CPU measurements
    still ride along as finite numbers, but a SKIP record claims no
    serving result (the honesty rule: never nan inside an OK artifact).
    The headline is min-of-passes with ``spread_pct`` as the noise bar,
    the same accounting as the training bench."""
    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    from apex_tpu.inference import DecodeEngine
    from apex_tpu.models import GPTConfig, GPTModel

    if on_tpu:
        # the flagship train-bench config (head_dim 128 — same MXU-lane
        # reasoning); batch 16 holds a 2·12·16·8·1024·128 bf16 cache
        # (~800 MB) comfortably next to the bf16 params
        cfg = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                   num_layers=12, num_heads=8, tp_size=1, remat=False,
                   attention_impl="flash", scan_layers=False)
        batch, prompt_len, new_tokens, passes = 16, 512, 128, 3
        naive_tokens = 16  # O(s²)/token: a short honest sample suffices
        cast = jnp.bfloat16
    else:  # smoke scale; the record is SKIP either way
        cfg = dict(vocab_size=256, max_seq_len=128, hidden_size=64,
                   num_layers=2, num_heads=4, tp_size=1, remat=False,
                   attention_impl="flash")
        batch, prompt_len, new_tokens, passes = 2, 32, 16, 2
        naive_tokens = 8
        cast = None

    model = GPTModel(GPTConfig(**cfg))
    params = model.init(jr.PRNGKey(0))
    if cast is not None:
        params = jax.tree.map(lambda x: x.astype(cast), params)
    engine = DecodeEngine(model, cache_dtype=cast)
    prompt = jr.randint(jr.PRNGKey(1), (batch, prompt_len), 0,
                        cfg["vocab_size"])
    key = jr.PRNGKey(2)

    # compile+warm both steps, then time: prefill passes first
    cache, tok, _ = engine.prefill(params, prompt, key)
    cache, tok, _ = engine.decode_step(params, cache, tok,
                                       jnp.int32(prompt_len), key)
    jax.block_until_ready(tok)
    pre_times = []
    for _ in range(passes):
        t0 = time.perf_counter()
        cache, tok, _ = engine.prefill(params, prompt, key)
        jax.block_until_ready(tok)
        pre_times.append(time.perf_counter() - t0)
    prefill_ms = min(pre_times) * 1e3

    # decode passes: each decodes new_tokens from a fresh prefill; only
    # the decode loop is inside the clock
    times = []
    for _ in range(passes):
        cache, tok, _ = engine.prefill(params, prompt, key)
        jax.block_until_ready(tok)
        t0 = time.perf_counter()
        for t in range(new_tokens):
            cache, tok, _ = engine.decode_step(
                params, cache, tok, jnp.int32(prompt_len + t), key)
        jax.block_until_ready(tok)
        times.append(time.perf_counter() - t0)
    tokens_per_s = batch * new_tokens / min(times)
    spread = (max(times) - min(times)) / min(times)
    # the zero-recompile contract is part of what is being measured: a
    # re-trace inside the timed loop would be dispatch overhead, not decode
    assert engine.decode_step._cache_size() == 1, \
        "decode_step re-traced during the bench (unstable avals?)"

    fields = dict(
        tokens_per_s=round(tokens_per_s, 1),
        prefill_ms=round(prefill_ms, 2),
        spread_pct=round(spread * 100, 2),
        batch=batch, prompt_len=prompt_len, new_tokens=new_tokens,
        max_seq_len=cfg["max_seq_len"],
        pass_times_ms=[round(t * 1e3, 2) for t in times],
        config=cfg, backend=jax.default_backend(),
    )

    if on_tpu:
        # naive recompute baseline: full forward over the whole prefix per
        # token — what serving WITHOUT the cache costs
        S = prompt_len + naive_tokens

        def naive_step(params, seq, pos):
            logits = model.logits(params, seq)
            last = jax.lax.dynamic_slice_in_dim(
                logits, pos - 1, 1, axis=1)[:, 0]
            nxt = jnp.argmax(last, -1).astype(seq.dtype)
            return jax.lax.dynamic_update_slice(
                seq, nxt[:, None], (jnp.int32(0), pos)), nxt

        naive = jax.jit(naive_step, donate_argnums=(1,))
        seq0 = jnp.zeros((batch, S), prompt.dtype).at[:, :prompt_len].set(
            prompt)
        seq, _ = naive(params, seq0, jnp.int32(prompt_len))  # compile+warm
        jax.block_until_ready(seq)
        ntimes = []
        for _ in range(passes):
            seq = jnp.zeros((batch, S), prompt.dtype
                            ).at[:, :prompt_len].set(prompt)
            jax.block_until_ready(seq)
            t0 = time.perf_counter()
            for t in range(naive_tokens):
                seq, nxt = naive(params, seq, jnp.int32(prompt_len + t))
            jax.block_until_ready(nxt)
            ntimes.append(time.perf_counter() - t0)
        naive_tps = batch * naive_tokens / min(ntimes)
        fields.update(naive_tokens_per_s=round(naive_tps, 1),
                      vs_naive=round(tokens_per_s / naive_tps, 4))
        status = "OK"
    else:
        reason = (f"decode serving throughput is a TPU measurement; this "
                  f"is a {jax.default_backend()} smoke run")
        fields.update(
            naive_tokens_per_s=("skipped", reason),
            vs_naive=("skipped", reason),
            reason=reason)
        status = "SKIP"

    if monitor.enabled():
        record = monitor.get_registry().emit_decode(status, **fields)
    else:  # sink-less registry: same construction+honesty path, no file
        record = monitor.MetricsRegistry().emit_decode(status, **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(f"decode bench record failed validation: {errors}")
    print(json.dumps(record))


#: seed of the serve sweep's Poisson trace — a fixed, recorded constant
#: so every sweep is replayable (the `trace_seed` field in the record)
SERVE_TRACE_SEED = 0


def build_serve_trace(seed, n_req, offered_rps, vocab, prompt_rng,
                      newtok_rng, sys_prompt_len=0, n_sys_prompts=2,
                      share_frac=0.5):
    """The serve sweep's request trace, fully determined by ``seed``:
    Poisson arrivals at ``offered_rps``, mixed prompt/output lengths,
    and — when ``sys_prompt_len > 0`` — a ``share_frac`` fraction of
    requests prefixed with one of ``n_sys_prompts`` shared system
    prompts (the chat/agent workload the prefix cache exists for).
    Same seed → token-identical requests and arrival times: sweeps are
    replayable (pinned by ``tests/test_serving.py``)."""
    import numpy as np

    from apex_tpu.serving import Request

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, n_req))
    sys_prompts = [
        rng.integers(0, vocab, sys_prompt_len).astype(np.int32)
        for _ in range(n_sys_prompts)
    ] if sys_prompt_len > 0 else []
    requests = []
    for i in range(n_req):
        tail = rng.integers(
            0, vocab,
            int(rng.integers(prompt_rng[0],
                             prompt_rng[1] + 1))).astype(np.int32)
        if sys_prompts and rng.random() < share_frac:
            sysp = sys_prompts[int(rng.integers(len(sys_prompts)))]
            prompt = np.concatenate([sysp, tail])
        else:
            # same TOTAL length distribution as the shared population —
            # a fresh random prefix instead of a shared one, so the
            # hit-vs-miss TTFT split measures the cache, not a
            # prompt-length skew
            pad = rng.integers(0, vocab,
                               sys_prompt_len).astype(np.int32)
            prompt = np.concatenate([pad, tail]) if sys_prompt_len \
                else tail
        requests.append(Request(
            rid=i, prompt=prompt,
            max_new_tokens=int(rng.integers(newtok_rng[0],
                                            newtok_rng[1] + 1)),
            arrival_s=float(arrivals[i])))
    return requests


def serve_main():
    """``python bench.py --serve`` — the continuous-batching serving leg:
    an offered-load sweep (seeded Poisson arrivals, mixed prompt/output
    lengths, shared system prompts) through
    :class:`apex_tpu.serving.ServingEngine` — paged KV blocks with
    copy-on-write prefix caching, optimistic admission + preemption,
    chunked prefill, SLO-aware dispatch, fused sampling tail — measuring
    p50/p99 per-token latency, TTFT split by prefix-cache hit vs miss,
    decode tokens/s/chip under churn, and slot occupancy, plus the
    witnesses: greedy tokens IDENTICAL to the single-request
    ``DecodeEngine`` both with no churn AND across the sweep including
    evicted-and-recomputed and prefix-hit requests (``churn_parity``),
    with both jitted steps' cache size pinned at 1 across the whole
    hit/miss/evict/readmit schedule.

    The pool is deliberately sized BELOW worst-case-everything and the
    offered load runs 4x the tier-1 sweep (64 rps vs the 16 the PR-7
    leg drove): exhaustion must engage preemption (bounded p99, the
    ``evict`` lifecycle trail) instead of stalling admission.

    Emits ONE ``serve`` record through the monitor schema (and onto the
    ``APEX_TPU_MONITOR`` stream when enabled) and prints it as one JSON
    line. On TPU the record is ``status: "OK"``; off-TPU it is an
    explicit ``status: "SKIP"`` with a reason — the smoke-scale CPU
    measurements ride along as finite numbers, but a SKIP record claims
    no serving result (the honesty rule: never nan inside an OK
    artifact).

    Request-level telemetry (ISSUE 10) rides the churn sweep: a
    :class:`apex_tpu.serving.ServeTelemetry` feeds bounded-memory
    streaming histograms (replacing the r7 host sample lists), emits
    per-request ``serve_event`` lifecycle records and periodic
    ``serve_window`` SLO records onto the monitor stream, and the final
    record carries the ``serve_anomaly`` section, admission-pressure
    counts, prefix-cache/preemption fields, and the MEASURED telemetry
    overhead (``telemetry_overhead_pct`` — the <1%-of-a-serve-step
    budget, reported rather than assumed)."""
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    from apex_tpu.inference import DecodeEngine
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import Request, ServeTelemetry, ServingEngine

    if on_tpu:
        # the flagship decode-bench config; 8 slots x 1024 rows of bf16
        # paged cache; the pool is sized to ~60% of worst-case-
        # everything so the 4x offered load actually exercises
        # preemption (the point of serving tier 2)
        cfg = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                   num_layers=12, num_heads=8, tp_size=1, remat=False,
                   attention_impl="flash", scan_layers=False)
        slots, block, chunk = 8, 128, 256
        n_req, offered_rps = 64, 64.0   # 4x the PR-7 sweep's 16 rps
        num_blocks = 41                 # 40 allocatable of 64 worst-case
        prompt_rng, newtok_rng = (64, 512), (16, 128)
        sys_prompt_len = 256            # 2 shared full blocks
        parity_prompt, parity_new = 512, 64
        n_parity = 6
        cast = jnp.bfloat16
    else:  # smoke scale; the record is SKIP either way
        cfg = dict(vocab_size=256, max_seq_len=128, hidden_size=64,
                   num_layers=2, num_heads=4, tp_size=1, remat=False,
                   attention_impl="flash")
        slots, block, chunk = 2, 16, 32
        n_req, offered_rps = 8, 2000.0
        num_blocks = 9                  # 8 allocatable of 16 worst-case
        prompt_rng, newtok_rng = (4, 40), (2, 10)
        sys_prompt_len = 32             # 2 shared full blocks
        parity_prompt, parity_new = 16, 8
        n_parity = 8
        cast = None

    model = GPTModel(GPTConfig(**cfg))
    params = model.init(jr.PRNGKey(0))
    if cast is not None:
        params = jax.tree.map(lambda x: x.astype(cast), params)
    engine = ServingEngine(model, num_slots=slots, block_size=block,
                           prefill_chunk=chunk, num_blocks=num_blocks,
                           cache_dtype=cast)

    # --- no-churn witnesses: one greedy request, both engines ---------------
    deng = DecodeEngine(model, cache_dtype=cast)
    prompt = np.asarray(jr.randint(jr.PRNGKey(1), (parity_prompt,), 0,
                                   cfg["vocab_size"]), np.int32)
    # first passes compile both stacks AND witness greedy parity; the
    # second, warm passes below carry the throughput ratio
    want = deng.generate(params, jnp.asarray(prompt)[None], parity_new)
    jax.block_until_ready(want)
    # rid -1 is reserved for engine-level telemetry events; the two
    # warmup/parity requests take ids far above the sweep's, and all
    # warm/timed runs pass telemetry=False — the auto-attached tracker
    # would bill emit costs to the paged side of vs_single_request that
    # the DecodeEngine baseline does not pay, and its windows would
    # double-count against the sweep's serve_windows field
    done = engine.serve(params, [Request(rid=1_000_000, prompt=prompt,
                                         max_new_tokens=parity_new)],
                        telemetry=False)
    greedy_parity = (np.asarray(done[0].tokens)
                     == np.asarray(want)[0]).all()
    t0 = time.perf_counter()
    want = deng.generate(params, jnp.asarray(prompt)[None], parity_new)
    jax.block_until_ready(want)
    single_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    engine.serve(params, [Request(rid=1_000_001, prompt=prompt,
                                  max_new_tokens=parity_new)],
                 telemetry=False)
    paged_s = time.perf_counter() - t0
    single_tps = parity_new / single_s
    vs_single = (parity_new / paged_s) / single_tps

    # --- the churn sweep: seeded Poisson arrivals, mixed lengths, ------------
    # shared system prompts (the prefix-cache workload). Same seed →
    # identical trace: the sweep is replayable.
    requests = build_serve_trace(
        SERVE_TRACE_SEED, n_req, offered_rps, cfg["vocab_size"],
        prompt_rng, newtok_rng, sys_prompt_len=sys_prompt_len)
    # the telemetry layer: streaming histograms (bounded memory — the
    # r7 per-token host lists are gone from this aggregation), lifecycle
    # + window records on the monitor stream, anomaly detection. The
    # claim its window records carry matches the final record's.
    skip_reason = (None if on_tpu else
                   f"continuous-batching latency/throughput is a TPU "
                   f"measurement; this is a {jax.default_backend()} "
                   f"smoke run at {n_req} requests")
    tel = ServeTelemetry(
        slots=slots, window_s=0.25 if on_tpu else 0.01,
        slo_ttft_ms=1000.0 if on_tpu else 10000.0,
        status="OK" if on_tpu else "SKIP", reason=skip_reason,
        # keep the raw lifecycle ledger in memory: the per-request TTFT
        # attribution below consumes it whether or not a JSONL sink is
        # attached
        collect_events=True)
    sched = engine.make_scheduler()
    t0 = time.perf_counter()
    done = engine.serve(params, requests, scheduler=sched, telemetry=tel)
    wall = time.perf_counter() - t0
    assert len(done) == n_req, "serve lost requests"
    stats = engine.last_stats

    total_tokens = sum(len(r.tokens) for r in done)
    # the zero-recompile contract IS part of what is measured: any
    # re-trace across this hit/miss/evict/readmit churn schedule would
    # be dispatch overhead — and it must hold WITH telemetry attached
    # (lifecycle records are emitted outside the jitted steps)
    jit_cache_ok = (engine.prefill_chunk._cache_size() == 1
                    and engine.decode_step._cache_size() == 1)
    assert jit_cache_ok, \
        "serving steps re-traced under churn (unstable avals?)"
    # pool accounting must be refcount-exact after the sweep: no leak,
    # and every live block a cache-resident (warm prefix, not demand)
    sched.allocator.check_accounting()
    assert sched.allocator.num_live == sched.allocator.num_resident, \
        "blocks live beyond the prefix cache's residents after drain"

    # greedy parity ACROSS the churn sweep, prioritizing the requests
    # the tier-2 machinery touched: evicted-and-recomputed streams and
    # prefix-cache hits must be token-identical to the unpreempted,
    # uncached DecodeEngine baseline (capped: each distinct prompt
    # length compiles one baseline prefill)
    touched = [r for r in done
               if r.evictions > 0 or r.prefix_hit_blocks > 0]
    untouched = [r for r in done if not (r.evictions > 0
                                         or r.prefix_hit_blocks > 0)]
    checked = (touched + untouched)[:n_parity]
    churn_parity = True
    for r in checked:
        want = np.asarray(deng.generate(
            params, jnp.asarray(r.prompt)[None], r.max_new_tokens))[0]
        ok = (len(r.tokens) == r.max_new_tokens
              and (np.asarray(r.tokens) == want).all())
        churn_parity = churn_parity and bool(ok)

    fields = dict(
        tokens_per_s=round(total_tokens / wall, 1),
        # streaming-histogram quantiles (parity with the removed
        # sample-list math within one bucket width — pinned by
        # tests/test_histogram.py) + the tier-2 prefix/preemption view
        **tel.final_fields(sched.allocator, sched),
        telemetry_overhead_pct=round(100.0 * tel.overhead_s / wall, 4),
        occupancy_pct=round(stats.occupancy_pct(slots), 2),
        vs_single_request=round(vs_single, 4),
        single_request_tokens_per_s=round(single_tps, 1),
        offered_rps=offered_rps,
        greedy_parity=bool(greedy_parity),
        churn_parity=bool(churn_parity),
        churn_parity_checked=len(checked),
        jit_cache_ok=bool(jit_cache_ok),
        trace_seed=SERVE_TRACE_SEED,
        requests=n_req, slots=slots, block_size=block,
        num_blocks=engine.num_blocks,
        blocks_high_water=stats.blocks_high_water,
        prefill_chunk=chunk,
        decode_steps=stats.decode_steps,
        prefill_chunks=stats.prefill_chunks,
        max_seq_len=engine.max_s,
        config=cfg, backend=jax.default_backend(),
    )
    if on_tpu:
        status = "OK"
    else:
        fields["reason"] = skip_reason
        status = "SKIP"

    if monitor.enabled():
        record = monitor.get_registry().emit_serve(status, **fields)
    else:  # sink-less registry: same construction+honesty path, no file
        record = monitor.MetricsRegistry().emit_serve(status, **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(f"serve bench record failed validation: {errors}")
    print(json.dumps(record))

    # --- per-request TTFT/latency attribution over the same sweep ------------
    # decompose every finished request's e2e latency into queue /
    # prefill / decode / spec / preempt / swap components from the
    # telemetry ledger (collect_events=True above — no sink needed) and
    # ship the summary as a second record; status mirrors the serve
    # record's (a SKIP sweep prices nothing)
    attr = monitor_trace.serve_attribution(tel.events, per_request=False)
    if status == "SKIP":
        attr.setdefault("reason", skip_reason)
    if monitor.enabled():
        record = monitor.get_registry().emit_serve_attribution(
            status, **attr)
    else:
        record = monitor.MetricsRegistry().emit_serve_attribution(
            status, **attr)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(
            f"serve_attribution record failed validation: {errors}")
    print(json.dumps(record))


def build_load_shift_trace(seed, n_calm, calm_rps, n_burst, burst_rps,
                           vocab, prompt_rng, newtok_rng,
                           sys_prompt_len=0, gap_s=0.05):
    """A two-regime serve trace, fully determined by ``seed``: a CALM
    segment at ``calm_rps`` followed (after ``gap_s``) by a BURST
    segment at ``burst_rps`` — the load shift the online
    :class:`~apex_tpu.serving.ReplanPolicy` exists for. Rids are
    contiguous across the two segments; same seed → token-identical
    trace (the replay fixture ``tests/test_serve_plan.py`` prices)."""
    from apex_tpu.serving import Request

    calm = build_serve_trace(seed, n_calm, calm_rps, vocab, prompt_rng,
                             newtok_rng, sys_prompt_len=sys_prompt_len)
    burst = build_serve_trace(seed + 1, n_burst, burst_rps, vocab,
                              prompt_rng, newtok_rng,
                              sys_prompt_len=sys_prompt_len)
    offset = (calm[-1].arrival_s if calm else 0.0) + gap_s
    shifted = [Request(rid=n_calm + i, prompt=r.prompt,
                       max_new_tokens=r.max_new_tokens,
                       arrival_s=float(r.arrival_s) + offset)
               for i, r in enumerate(burst)]
    return calm + shifted


def plan_serve_main(argv=None):
    """``python bench.py --serve --plan-serve [--costdb F]`` — the
    serving-plan leg (ISSUE 20): search → pick → measure → re-plan, the
    ``--plan`` discipline applied to the SERVING knobs.

    **Search**: record the seeded load-shift trace
    (:func:`build_load_shift_trace`), serve it once under the HAND
    config to calibrate the replay model's per-phase costs from the
    telemetry ledger (chunk-prefill ms/token, per-dispatch decode ms —
    the PR-16 attribution terms; CostDB rates + conservative floors
    cover the rest, every unpriced term a flagged ``uncalibrated`` key,
    never silently defaulted), then replay the trace through the
    host-side discrete-event model for every candidate on the grid
    (:func:`apex_tpu.plan.serve.search_serve_plans`) and rank by
    predicted tokens/s.

    **Pick + measure**: the searched winner is served on the SAME
    recorded trace and its measured tokens/s lands next to the
    prediction — ``predicted_vs_measured_err_pct`` is the honesty
    series ``tools/bench_history.py`` gates (absolute points), and
    ``searched_beats_hand`` witnesses the headline: the searched plan
    beats the hand config on the recorded trace (tokens/s AND TTFT
    p50, compared on the bit-deterministic replay pricing — the same
    model both plans are priced by).

    **Re-plan**: the trace is served a third time under a
    :class:`~apex_tpu.serving.ReplanPolicy` two-plan ladder (calm →
    loaded, aval-stable diffs only); the burst must trigger at least
    one live mid-serve switch (``replans``), greedy output must stay
    token-identical across it (``replan_parity``), and both jit caches
    stay pinned at 1 (``jit_cache_ok``) — the zero-recompile contract
    IS part of what is measured.

    Emits ONE schema-validated ``serve_plan`` record. On TPU it is
    ``status: "OK"``; off-TPU an explicit ``SKIP`` with a reason — the
    measured half rides as explicit skip objects (never nan in an OK
    line) with ``smoke_tokens_per_s`` as the finite plumbing witness."""
    import sys

    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    from apex_tpu.inference import DecodeEngine
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.plan import (
        ServePlan,
        conservative_defaults,
        derive_serve_costs,
        price_serve_plan,
        search_serve_plans,
        serve_plan_record_fields,
    )
    from apex_tpu.prof.calibrate import validate_costdb
    from apex_tpu.serving import ReplanPolicy, ServeTelemetry, ServingEngine

    argv = list(sys.argv[1:] if argv is None else argv)
    costdb_path = (argv[argv.index("--costdb") + 1]
                   if "--costdb" in argv else None)

    if on_tpu:
        # the serve_main flagship config as the HAND plan: the baseline
        # the search must beat on its own recorded trace
        cfg = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                   num_layers=12, num_heads=8, tp_size=1, remat=False,
                   attention_impl="flash", scan_layers=False)
        hand = ServePlan(num_blocks=41, block_size=128, num_slots=8,
                         prefill_chunk=256, max_prefill_share=4,
                         slo_ttft_ms=1000.0)
        n_calm, calm_rps, n_burst, burst_rps = 16, 16.0, 48, 128.0
        prompt_rng, newtok_rng = (64, 512), (16, 128)
        sys_prompt_len, window_s = 256, 0.25
        n_parity = 6
        cast = jnp.bfloat16
    else:  # smoke scale; the record is SKIP either way
        cfg = dict(vocab_size=256, max_seq_len=128, hidden_size=64,
                   num_layers=2, num_heads=4, tp_size=1, remat=False,
                   attention_impl="flash")
        hand = ServePlan(num_blocks=9, block_size=16, num_slots=2,
                         prefill_chunk=32, max_prefill_share=4,
                         slo_ttft_ms=10000.0)
        # calm trickle then a burst arriving faster than 2 slots drain:
        # the ~60 ms arrival ramp spans several 10 ms windows, so the
        # queue grows monotonically across at least three of them — the
        # buildup detector MUST fire and the ladder MUST step up
        n_calm, calm_rps, n_burst, burst_rps = 4, 40.0, 24, 400.0
        prompt_rng, newtok_rng = (4, 40), (2, 10)
        sys_prompt_len, window_s = 32, 0.01
        n_parity = 4
        cast = None

    model = GPTModel(GPTConfig(**cfg))
    params = model.init(jr.PRNGKey(0))
    if cast is not None:
        params = jax.tree.map(lambda x: x.astype(cast), params)
    requests = build_load_shift_trace(
        SERVE_TRACE_SEED, n_calm, calm_rps, n_burst, burst_rps,
        cfg["vocab_size"], prompt_rng, newtok_rng,
        sys_prompt_len=sys_prompt_len)
    n_req = len(requests)
    skip_reason = (None if on_tpu else
                   f"serving-plan throughput/TTFT is a TPU measurement; "
                   f"this is a {jax.default_backend()} smoke run at "
                   f"{n_req} requests")

    def _measured_serve(plan, policy=None):
        """Serve the recorded trace under ``plan``: (tokens/s, TTFT
        p50 ms, telemetry, engine, done results, wall s)."""
        from apex_tpu.serving import Request

        eng = ServingEngine(model, cache_dtype=cast,
                            **plan.engine_kwargs())
        # warm both jitted steps BEFORE the timed trace: a cold compile
        # inside the sweep would stall the serve clock past every
        # arrival and poison both the measured costs and the window
        # telemetry the re-planner keys on (rid far above the sweep's)
        warm_prompt = np.asarray(jr.randint(
            jr.PRNGKey(2), (plan.prefill_chunk,), 0,
            cfg["vocab_size"]), np.int32)
        eng.serve(params, [Request(rid=1_000_000, prompt=warm_prompt,
                                   max_new_tokens=4)], telemetry=False)
        tel = ServeTelemetry(
            slots=plan.num_slots, window_s=window_s,
            status="OK" if on_tpu else "SKIP", reason=skip_reason,
            collect_events=True, **plan.telemetry_kwargs())
        sched = eng.make_scheduler(policy=policy)
        # Request objects carry their RESULT fields (tokens, stamps):
        # each replay leg serves fresh copies of the recorded trace
        replay = [Request(rid=r.rid, prompt=r.prompt,
                          max_new_tokens=r.max_new_tokens,
                          arrival_s=r.arrival_s) for r in requests]
        t0 = time.perf_counter()
        done = eng.serve(params, replay, scheduler=sched, telemetry=tel)
        wall = time.perf_counter() - t0
        assert len(done) == n_req, "serve lost requests"
        tokens = sum(len(r.tokens) for r in done)
        ttfts = sorted(e["ttft_ms"] for e in tel.events
                       if e.get("phase") == "first_token")
        p50 = (ttfts[max(0, -(-len(ttfts) // 2) - 1)] if ttfts
               else float("nan"))
        return tokens / wall, p50, tel, eng, done, wall

    # --- calibrate: the hand-config serve is the measured-cost source --------
    hand_tps, hand_p50, tel, eng, done, wall = _measured_serve(hand)
    stats = eng.last_stats
    prefill_ms = sum(e.get("prefill_ms") or 0.0 for e in tel.events
                     if e.get("phase") == "first_token")
    live_prefill = sum(
        max(len(r.prompt) - r.prefix_hit_blocks * hand.block_size, 1)
        for r in done)
    measured = dict(
        prefill_ms_per_token=max(prefill_ms / max(live_prefill, 1), 1e-6),
        # per-DISPATCH cost at the hand config's average live width (the
        # per-row split stays a CostDB term); on the deliberately
        # overloaded trace the non-prefill wall is decode-dominated
        decode_ms_per_step=max(
            (wall * 1e3 - prefill_ms) / max(stats.decode_steps, 1), 1e-6),
    )

    if costdb_path:
        with open(costdb_path) as fh:
            db = json.load(fh)
        errors = validate_costdb(db)
        if errors:
            raise ValueError(f"{costdb_path} is not a valid costdb: "
                             f"{errors}")
        source = costdb_path
    else:
        # no measured CostDB: every db-priced key is a flagged blind
        # spot at the conservative floors — labeled, never silent
        db = {"schema": 1, "kind": "costdb", "collectives": {},
              "gemms": {}}
        source = "uniform-reference"
    costs = derive_serve_costs(
        db, hidden_size=cfg["hidden_size"], num_layers=cfg["num_layers"],
        num_heads=cfg["num_heads"], vocab_size=cfg["vocab_size"],
        measured=measured, **conservative_defaults(db))

    # --- search the grid on the recorded trace -------------------------------
    result = search_serve_plans(requests, costs, base=hand)
    best = result.best
    hand_price = price_serve_plan(hand, requests, costs)
    # the headline comparison, on the SAME bit-deterministic replay
    # pricing both plans ride (predicted↔measured drift is gated
    # separately via predicted_vs_measured_err_pct)
    beats = (best.price.predicted_tokens_per_s
             > hand_price.predicted_tokens_per_s
             and best.price.predicted_ttft_p50_ms
             <= hand_price.predicted_ttft_p50_ms)

    # --- measure the searched winner on the same trace -----------------------
    best_tps, best_p50, _tel2, eng2, _done2, _w2 = _measured_serve(
        best.plan)

    # --- live re-plan under the load shift -----------------------------------
    # a calm → loaded ladder over the SEARCHED plan's aval geometry:
    # only aval-stable knobs differ (share bound, admission, SLO), so
    # every switch applies live and the jit caches must stay at 1
    calm_plan = best.plan
    loaded_plan = ServePlan(**{
        **calm_plan.to_json(),
        "max_prefill_share": max(calm_plan.max_prefill_share, 4),
        "admission": "short_first",
        "slo_ttft_ms": None,
    })
    policy = ReplanPolicy(plans=(calm_plan, loaded_plan))
    rp_tps, _rp_p50, tel3, eng3, done3, _w3 = _measured_serve(
        calm_plan, policy=policy)
    jit_cache_ok = (eng3.prefill_chunk._cache_size() == 1
                    and eng3.decode_step._cache_size() == 1)
    assert jit_cache_ok, \
        "re-planned serving steps re-traced (unstable avals?)"
    assert tel3.replans >= 1 and policy.replans == tel3.replans, \
        "the load shift produced no live re-plan (buildup never fired?)"
    # greedy parity ACROSS the switch: finished streams token-identical
    # to the unchurned DecodeEngine baseline
    deng = DecodeEngine(model, cache_dtype=cast)
    replan_parity = True
    for r in done3[:n_parity]:
        want = np.asarray(deng.generate(
            params, jnp.asarray(r.prompt)[None], r.max_new_tokens))[0]
        ok = (len(r.tokens) == r.max_new_tokens
              and (np.asarray(r.tokens) == want).all())
        replan_parity = replan_parity and bool(ok)

    fields = serve_plan_record_fields(
        result, costdb_source=source,
        measured_tokens_per_s=best_tps if on_tpu else None,
        measured_ttft_p50_ms=best_p50 if on_tpu else None,
        skip_reason=skip_reason)
    skip = lambda r: ("skipped", r)  # noqa: E731
    fields.update(
        hand_tokens_per_s=(round(hand_tps, 1) if on_tpu
                           else skip(skip_reason)),
        hand_ttft_p50_ms=(round(hand_p50, 3) if on_tpu
                          else skip(skip_reason)),
        searched_beats_hand=bool(beats),
        replans=int(tel3.replans),
        replan_parity=bool(replan_parity),
        jit_cache_ok=bool(jit_cache_ok),
        smoke_tokens_per_s=round(best_tps, 1),
        trace_seed=SERVE_TRACE_SEED,
        config=cfg, backend=jax.default_backend(),
    )
    if on_tpu:
        status = "OK"
    else:
        fields["reason"] = skip_reason
        status = "SKIP"

    if monitor.enabled():
        record = monitor.get_registry().emit_serve_plan(status, **fields)
    else:  # sink-less registry: same construction+honesty path, no file
        record = monitor.MetricsRegistry().emit_serve_plan(status,
                                                           **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(
            f"serve_plan bench record failed validation: {errors}")
    print(json.dumps(record))


def tp_serve_main(argv):
    """``python bench.py --serve --plan-tp N`` — the tensor-parallel
    serving leg (ISSUE 17): serve a model bigger than one chip.

    * **Sharded churn sweep**: the seeded serve trace through a
      ``plan=ParallelPlan(tp=N)`` :class:`~apex_tpu.serving.
      ServingEngine` — paged KV pool sharded over kv_heads, QKV/output
      projections riding the ring-overlap collective matmuls, the
      sampling tail psum-composed — vs the tp=1 engine on the SAME
      trace, with the greedy whole-sweep token-parity witness and both
      jit caches pinned at 1.
    * **Collective traffic**: the decode step's ``ppermute`` ring
      calls/bytes from the :func:`~apex_tpu.monitor.hooks.
      count_collective` counters the rings bump at trace time (one
      trace == one step's traffic under the pinned-cache contract).
    * **Disaggregated prefill→decode**: a prefill-role engine serves
      the requests to first token (its TTFT stands alone), the KV
      chains stream through :mod:`apex_tpu.serving.disagg` (manifest +
      sha256 block digests across a directory boundary), a decode-role
      engine ingests and finishes them — output token-identical to the
      monolithic run (``handoff_parity``), transfer bytes/blocks/wall
      in the record, ``handoff`` lifecycle events carrying one
      trace_id across both roles.

    Emits ONE schema-validated ``tp_serve`` record (CLOSED — junk keys
    fail) and prints it as one JSON line. ``status: "OK"`` only on a
    real TPU with >= N chips; anywhere else (CPU virtual mesh, too few
    chips) the record is an explicit ``status: "SKIP"`` with a reason —
    the smoke measurements ride along as finite numbers, never nan in
    an OK line."""
    import tempfile

    import numpy as np

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.plan.parallel_plan import ParallelPlan
    from apex_tpu.serving import (Request, ServeTelemetry, ServingEngine,
                                  export_handoff, ingest_handoff,
                                  prefill_requests, read_handoff,
                                  write_handoff)

    tp = 2
    if "--plan-tp" in argv:
        i = argv.index("--plan-tp")
        if i + 1 < len(argv):
            tp = int(argv[i + 1])
    monitor.enable_from_env()
    if not monitor.enabled():
        # memory-only registry: the ring-traffic counters (and the
        # record's construction+honesty path) need one even without a
        # JSONL sink attached
        monitor.enable()
    reg = monitor.get_registry()

    on_tpu = (jax.default_backend() == "tpu"
              and len(jax.devices()) >= tp)
    if on_tpu:
        cfg = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                   num_layers=12, num_heads=8, tp_size=1, remat=False,
                   attention_impl="flash", scan_layers=False)
        slots, block, chunk = 8, 128, 256
        n_req, offered_rps = 64, 64.0
        num_blocks = 65
        prompt_rng, newtok_rng = (64, 512), (16, 128)
        sys_prompt_len = 256
        hand_n, hand_prompt, hand_new = 6, (256, 512), (16, 64)
    else:
        cfg = dict(vocab_size=256, max_seq_len=128, hidden_size=64,
                   num_layers=2, num_heads=4, tp_size=1, remat=False,
                   attention_impl="flash")
        slots, block, chunk = 4, 16, 32
        n_req, offered_rps = 8, 2000.0
        num_blocks = 33
        prompt_rng, newtok_rng = (4, 40), (2, 10)
        sys_prompt_len = 32
        hand_n, hand_prompt, hand_new = 5, (18, 60), (4, 10)
    skip_reason = (
        None if jax.default_backend() == "tpu" and on_tpu else
        f"tp={tp} serving is a multichip-TPU measurement; this is a "
        f"{jax.default_backend()} run over "
        f"{min(tp, len(jax.devices()))} virtual-mesh devices"
        if jax.default_backend() != "tpu" else
        f"tp={tp} needs {tp} chips; this host has {len(jax.devices())}")

    model = GPTModel(GPTConfig(**cfg))
    params = model.init(jr.PRNGKey(0))

    def mk_engine(plan=None):
        return ServingEngine(model, num_slots=slots, block_size=block,
                             prefill_chunk=chunk, num_blocks=num_blocks,
                             plan=plan)

    trace_reqs = lambda: build_serve_trace(  # noqa: E731
        SERVE_TRACE_SEED, n_req, offered_rps, cfg["vocab_size"],
        prompt_rng, newtok_rng, sys_prompt_len=sys_prompt_len)

    # --- tp=1 baseline on the same trace ------------------------------------
    e1 = mk_engine()
    t0 = time.perf_counter()
    base = e1.serve(params, trace_reqs(), telemetry=False)
    base_wall = time.perf_counter() - t0
    base_toks = {r.rid: list(r.tokens) for r in base}
    base_tps = sum(len(r.tokens) for r in base) / base_wall

    # --- the sharded engine -------------------------------------------------
    plan = ParallelPlan(tp=tp)
    etp = mk_engine(plan)

    def ring_counters():
        return (int(reg.counters.get("collective/ppermute[tp]_calls", 0)),
                int(reg.counters.get("collective/ppermute[tp]_bytes", 0)))

    c0 = ring_counters()
    # one warm request first: the prefill program traces here, so the
    # counter delta across the main sweep isolates the decode trace —
    # under the pinned-cache contract one trace IS one step's traffic
    warm = etp.serve(params, [Request(rid=1_000_000,
                                      prompt=np.arange(block + 2,
                                                       dtype=np.int32),
                                      max_new_tokens=1)],
                     telemetry=False)
    assert len(warm) == 1
    c1 = ring_counters()
    tel = ServeTelemetry(
        slots=slots, window_s=0.25 if on_tpu else 0.01,
        slo_ttft_ms=1000.0 if on_tpu else 10000.0,
        status="OK" if on_tpu else "SKIP", reason=skip_reason,
        collect_events=True)
    t0 = time.perf_counter()
    done = etp.serve(params, trace_reqs(), telemetry=tel)
    tp_wall = time.perf_counter() - t0
    c2 = ring_counters()
    stats = etp.last_stats
    tp_toks = {r.rid: list(r.tokens) for r in done}
    greedy_parity = tp_toks == base_toks
    jit_cache_ok = (etp.prefill_chunk._cache_size() == 1
                    and etp.decode_step._cache_size() == 1)
    assert jit_cache_ok, \
        "tp serving steps re-traced under churn (unstable avals?)"
    ttft_mono = [1e3 * (r.first_token_s - r.submit_s) for r in done
                 if r.first_token_s is not None]
    # decode-step ring traffic: the sweep's trace-time delta (prefill
    # traced in the warm run above); zero means the decode trace
    # somehow ran early — report the conservative total then
    dec_calls, dec_bytes = c2[0] - c1[0], c2[1] - c1[1]
    tot_calls, tot_bytes = c2[0] - c0[0], c2[1] - c0[1]

    # --- disaggregated prefill -> decode handoff ----------------------------
    def hand_reqs():
        rng = np.random.default_rng(SERVE_TRACE_SEED + 17)
        return [Request(
            rid=i,
            prompt=rng.integers(0, cfg["vocab_size"],
                                int(rng.integers(*hand_prompt))
                                ).astype(np.int32),
            max_new_tokens=int(rng.integers(*hand_new)))
            for i in range(hand_n)]

    mono = mk_engine(plan).serve(params, hand_reqs(), telemetry=False)
    mono_toks = {r.rid: list(r.tokens) for r in mono}

    ep = mk_engine(plan)   # prefill role
    ed = mk_engine(plan)   # decode role (its own pool + scheduler)
    tel_hand = ServeTelemetry(slots=slots, status="OK" if on_tpu
                              else "SKIP", reason=skip_reason)
    sched_p = ep.make_scheduler()
    pre_done = ep.serve(params, prefill_requests(hand_reqs()),
                        scheduler=sched_p, telemetry=tel_hand)
    ttft_pre = [1e3 * (r.first_token_s - r.submit_s) for r in pre_done
                if r.first_token_s is not None]
    t0 = time.perf_counter()
    handoffs = [export_handoff(ep.last_pool, sched_p, r,
                               block_size=block, telemetry=tel_hand)
                for r in pre_done]
    with tempfile.TemporaryDirectory() as d:
        transfer_bytes = write_handoff(d, handoffs)
        streamed = read_handoff(d)   # digests verified per block here
    sched_d = ed.make_scheduler()
    pool_d, hstats = ingest_handoff(ed.init_pool(), sched_d, streamed,
                                    telemetry=tel_hand)
    transfer_ms = 1e3 * (time.perf_counter() - t0)
    dec_done = ed.serve(params, hand_reqs(), scheduler=sched_d,
                        pool=pool_d, telemetry=False)
    handoff_parity = ({r.rid: list(r.tokens) for r in dec_done}
                      == mono_toks)
    hit_all = all(r.prefix_hit_blocks > 0 for r in dec_done
                  if len(r.prompt) >= 2 * block)

    c = model.config
    row_bytes = (2 * c.num_layers * c.local_kv_heads * c.head_dim
                 * (2 if on_tpu else 4))
    pool_mb_total = num_blocks * block * row_bytes / 2 ** 20
    fields = dict(
        tp=tp,
        tokens_per_s=round(sum(len(r.tokens) for r in done) / tp_wall, 1),
        baseline_tokens_per_s=round(base_tps, 1),
        ttft_ms_prefill_role=round(float(np.mean(ttft_pre)), 3),
        ttft_ms_monolithic=round(float(np.mean(ttft_mono)), 3),
        handoff_blocks=hstats.blocks,
        handoff_transfer_bytes=transfer_bytes,
        handoff_transfer_ms=round(transfer_ms, 3),
        digests_verified=hstats.digests_verified,
        collective_ppermute_calls=tot_calls,
        collective_ppermute_bytes=tot_bytes,
        decode_steps=stats.decode_steps,
        collective_bytes_per_step=dec_bytes if dec_bytes else tot_bytes,
        greedy_parity=bool(greedy_parity),
        handoff_parity=bool(handoff_parity and hit_all
                            and hstats.skipped == 0),
        jit_cache_ok=bool(jit_cache_ok),
        kv_dtype="float",
        requests=n_req,
        num_blocks=num_blocks,
        pool_mb_per_shard=round(pool_mb_total / tp, 4),
        pool_mb_total=round(pool_mb_total, 4),
        config=cfg, backend=jax.default_backend(),
    )
    if on_tpu:
        status = "OK"
    else:
        fields["reason"] = skip_reason
        status = "SKIP"
    record = reg.emit_tp_serve(status, **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(
            f"tp_serve bench record failed validation: {errors}")
    print(json.dumps(record))


def spec_main(tree=False):
    """``python bench.py --spec`` — the speculative-decoding +
    quantized-KV leg (ROADMAP item 3, both factors of the decode-
    bandwidth attack in one artifact):

    * **Batch-1 speculation**: greedy generation through
      ``DecodeEngine.generate(draft=NGramDrafter(k))`` vs the plain
      decode loop — tokens/s/request both ways, the speedup ratio, the
      measured acceptance rate that explains it, and the greedy-parity
      witness (spec output token-identical to the baseline) with every
      jitted body's cache size pinned at 1.
    * **Speculation under churn**: the same comparison through
      ``ServingEngine.serve(draft=...)`` on a seeded multi-request
      trace — spec rounds interleaving with chunked prefill, block
      tables rewound to the accepted frontier each round — with the
      whole-sweep token parity witness (``churn_parity``).
    * **int8 KV quantization**: the ``kv_dtype="int8"`` pool vs the
      float parity oracle, decode logits TEACHER-FORCED through both
      on identical contexts so the reported ``kv_quant_logit_err`` is
      a per-position bound, not a divergence artifact; pool footprints
      for both ride along.

    With ``--tree`` the record additionally carries the TREE-speculation
    leg (:func:`_spec_tree_leg`): fused tree verify at batch 1 and under
    churn with the small-model drafter's KV in the SHARED paged pool,
    plus the adaptive-vs-fixed (depth, branching) witness on a recorded
    bimodal acceptance trace.

    Emits ONE schema-validated ``spec`` record (a CLOSED schema — junk
    keys fail) and prints it as one JSON line. On TPU the record is
    ``status: "OK"``; off-TPU it is an explicit ``status: "SKIP"`` with
    a reason — the smoke-scale measurements ride along as finite
    numbers, but a SKIP record claims no serving result. Never nan in
    an OK line."""
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    from apex_tpu.inference import DecodeEngine
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import ServingEngine
    from apex_tpu.spec import NGramDrafter

    if on_tpu:
        # the flagship decode-bench config; k=4 drafted tokens per round
        cfg = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                   num_layers=12, num_heads=8, tp_size=1, remat=False,
                   attention_impl="flash", scan_layers=False)
        prompt_len, new_tokens, passes, k = 512, 128, 3, 4
        slots, block, chunk, n_req = 4, 128, 128, 16
        quant_tokens = 32
        cast = jnp.bfloat16
    else:  # smoke scale; the record is SKIP either way
        cfg = dict(vocab_size=256, max_seq_len=256, hidden_size=64,
                   num_layers=2, num_heads=4, tp_size=1, remat=False,
                   attention_impl="flash")
        prompt_len, new_tokens, passes, k = 32, 16, 2, 4
        slots, block, chunk, n_req = 2, 16, 16, 6
        quant_tokens = 8
        cast = None

    model = GPTModel(GPTConfig(**cfg))
    params = model.init(jr.PRNGKey(0))
    if cast is not None:
        params = jax.tree.map(lambda x: x.astype(cast), params)
    # a self-similar prompt (a tiled pattern): speculation's payoff is
    # acceptance, and acceptance needs guessable continuations — this is
    # the honest analog of the code/chat traffic speculation targets
    pat = np.asarray(jr.randint(jr.PRNGKey(1), (max(prompt_len // 4, 1),),
                                0, cfg["vocab_size"]), np.int32)
    prompt = np.tile(pat, 4)[:prompt_len]
    deng = DecodeEngine(model, cache_dtype=cast)
    drafter = NGramDrafter(k=k)

    # compile + the parity witness; one "spec-" trace id spans both legs
    # (the generate calls reuse the ambient id, so their spans — and the
    # final spec record, stamped explicitly below — share it)
    spec_tid = monitor_trace.new_trace_id("spec")
    with monitor_trace.trace_context(spec_tid):
        want = np.asarray(deng.generate(params, jnp.asarray(prompt)[None],
                                        new_tokens))
        spec_out = np.asarray(deng.generate(
            params, jnp.asarray(prompt)[None], new_tokens, draft=drafter))
    greedy_parity = bool((spec_out == want).all())
    stats = deng.last_spec_stats
    jit_cache_ok = (deng.spec_verify_step._cache_size() == 1
                    and deng.decode_step._cache_size() == 1)

    # timed passes: min-of-passes headline, spread as the noise bar
    base_times, spec_times = [], []
    for _ in range(passes):
        t0 = time.perf_counter()
        out = deng.generate(params, jnp.asarray(prompt)[None], new_tokens)
        jax.block_until_ready(out)
        base_times.append(time.perf_counter() - t0)
    for _ in range(passes):
        t0 = time.perf_counter()
        out = deng.generate(params, jnp.asarray(prompt)[None], new_tokens,
                            draft=drafter)
        jax.block_until_ready(out)
        spec_times.append(time.perf_counter() - t0)
    tps_spec = new_tokens / min(spec_times)
    tps_base = new_tokens / min(base_times)
    spread = (max(spec_times) - min(spec_times)) / min(spec_times)

    # --- speculation under churn: the serving engine with spec rounds --------
    # the trace is seed-determined, so each run gets a FRESH but
    # token-identical request list (a served Request carries its output)
    def trace():
        return build_serve_trace(
            SERVE_TRACE_SEED, n_req, 2000.0, cfg["vocab_size"],
            (4, max(prompt_len // 2, 8)), (2, max(new_tokens // 2, 4)))

    base_eng = ServingEngine(model, num_slots=slots, block_size=block,
                             prefill_chunk=chunk, cache_dtype=cast)
    done = base_eng.serve(params, trace(), telemetry=False)
    base_tokens = {r.rid: list(r.tokens) for r in done}
    t0 = time.perf_counter()
    done = base_eng.serve(params, trace(), telemetry=False)
    churn_base_s = time.perf_counter() - t0
    spec_eng = ServingEngine(model, num_slots=slots, block_size=block,
                             prefill_chunk=chunk, cache_dtype=cast)
    done = spec_eng.serve(params, trace(), telemetry=False,
                          draft=NGramDrafter(k=k))
    churn_parity = all(list(r.tokens) == base_tokens[r.rid] for r in done)
    jit_cache_ok = (jit_cache_ok
                    and spec_eng.prefill_chunk._cache_size() == 1
                    and spec_eng.spec_step._cache_size() == 1
                    and spec_eng.decode_step._cache_size() <= 1)
    t0 = time.perf_counter()
    done = spec_eng.serve(params, trace(), telemetry=False,
                          draft=NGramDrafter(k=k))
    churn_spec_s = time.perf_counter() - t0
    total = sum(len(r.tokens) for r in done)
    tps_churn = total / churn_spec_s
    tps_churn_base = total / churn_base_s

    # --- int8 KV pool vs the float parity oracle -----------------------------
    kv_err, q_mb, o_mb = _spec_quant_err(
        model, params, prompt, quant_tokens, slots=1, block=block,
        chunk=chunk, cast=cast)

    # --- the --tree leg: fused tree verify + pooled drafter + adaptive k -----
    tree_fields = {}
    if tree:
        with monitor_trace.trace_context(spec_tid):
            tree_fields = _spec_tree_leg(
                model, params, deng, prompt, want, new_tokens, passes,
                cfg, slots=slots, block=block, chunk=chunk, cast=cast,
                trace=trace, base_tokens=base_tokens, tps_base=tps_base)

    fields = dict(
        tokens_per_s_request=round(tps_spec, 1),
        baseline_tokens_per_s_request=round(tps_base, 1),
        speedup=round(tps_spec / tps_base, 4),
        tokens_per_s_churn=round(tps_churn, 1),
        baseline_tokens_per_s_churn=round(tps_churn_base, 1),
        speedup_churn=round(tps_churn / tps_churn_base, 4),
        acceptance_rate=round(stats.acceptance_rate, 4),
        accepted_per_round=round(stats.accepted / stats.rounds, 3)
        if stats.rounds else 0.0,
        rounds=stats.rounds,
        draft_k=k, drafter="ngram",
        kv_dtype="int8",
        kv_quant_logit_err=round(kv_err, 5),
        kv_quant_pool_mb=round(q_mb, 3),
        kv_oracle_pool_mb=round(o_mb, 3),
        greedy_parity=greedy_parity,
        churn_parity=bool(churn_parity),
        jit_cache_ok=bool(jit_cache_ok),
        prompt_len=prompt_len, new_tokens=new_tokens, requests=n_req,
        spread_pct=round(spread * 100, 2),
        pass_times_ms=[round(t * 1e3, 2) for t in spec_times],
        config=cfg, backend=jax.default_backend(),
    )
    fields.update(tree_fields)
    assert greedy_parity and churn_parity, \
        "speculative decode diverged from the non-speculative baseline"
    assert jit_cache_ok, "a spec body re-traced (unstable avals?)"
    if on_tpu:
        status = "OK"
    else:
        fields["reason"] = (
            f"speculative-decode throughput is a TPU measurement; this "
            f"is a {jax.default_backend()} smoke run")
        status = "SKIP"

    if monitor.enabled():
        record = monitor.get_registry().emit_spec(status, trace_id=spec_tid,
                                                  **fields)
    else:  # sink-less registry: same construction+honesty path, no file
        record = monitor.MetricsRegistry().emit_spec(
            status, trace_id=spec_tid, **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(f"spec bench record failed validation: {errors}")
    print(json.dumps(record))


def _spec_quant_err(model, params, prompt, n_tokens, *, slots, block,
                    chunk, cast):
    """Max |Δlogit| between the int8 pool and the float parity oracle,
    TEACHER-FORCED: both engines decode the oracle's token stream on
    identical contexts, so the bound measures quantization, not
    divergence. Returns ``(max_err, int8_pool_mb, oracle_pool_mb)``."""
    import numpy as np

    from apex_tpu.serving import Request, ServingEngine

    prompt = np.asarray(prompt[:max(len(prompt) // 2, 4)], np.int32)
    key0 = jr.PRNGKey(0)

    def drive(engine, forced=None):
        sched = engine.make_scheduler(prefix_cache=False)
        sched.submit(Request(rid=0, prompt=prompt,
                             max_new_tokens=n_tokens))
        sched.admit(0.0)
        pool = engine.init_pool()
        while True:
            w = sched.next_prefill(0.0)
            if w is None:
                break
            pool, tok, _ = engine.prefill_chunk(
                params, pool, jnp.asarray(sched.tables.row(w.slot)),
                jnp.asarray(w.tokens), jnp.int32(w.start),
                jnp.int32(w.live), key0)
            sched.note_prefill(w, int(tok), 0.0)
        rows, toks_out = [], []
        for t in range(n_tokens - 1):
            batch = sched.decode_batch(0.0)
            if batch is None:
                break
            toks, lens = batch
            pool, sampled, logits = engine.decode_step(
                params, pool, jnp.asarray(sched.tables.asarray()),
                jnp.asarray(toks), jnp.asarray(lens), key0)
            sampled = np.asarray(sampled).copy()
            if forced is not None:  # teacher-force the oracle's stream
                sampled[0] = forced[t]
            rows.append(np.asarray(logits[0], np.float32))
            toks_out.append(int(sampled[0]))
            sched.note_decode(sampled, 0.0)
        return np.stack(rows), toks_out

    oracle = ServingEngine(model, num_slots=slots, block_size=block,
                           prefill_chunk=chunk, cache_dtype=cast)
    l_oracle, forced = drive(oracle)
    quant = ServingEngine(model, num_slots=slots, block_size=block,
                          prefill_chunk=chunk, cache_dtype=cast,
                          kv_dtype="int8")
    l_quant, _ = drive(quant, forced=forced)
    err = float(np.max(np.abs(l_quant - l_oracle)))
    return err, quant.pool_bytes() / 1e6, oracle.pool_bytes() / 1e6


def _spec_tree_leg(model, params, deng, prompt, want, new_tokens, passes,
                   cfg, *, slots, block, chunk, cast, trace, base_tokens,
                   tps_base):
    """The ``--tree`` extension of the spec leg, three witnesses:

    * **Batch-1 tree verify**: ``DecodeEngine.generate`` with an
      :class:`NGramTreeDrafter` vs the plain-decode output already in
      hand — greedy tree output must be TOKEN-IDENTICAL (the deepest-
      fully-accepted-path winner is exactly the greedy chain), with the
      tree-verify body's jit cache pinned at one entry.
    * **Churn with a pooled drafter**: the same seeded trace through
      ``serve(draft=PagedModelDrafter(...))`` — the drafter's KV blocks
      come from the scheduler's OWN allocator, so the sweep also
      witnesses peak drafter blocks in the shared pool.
    * **Adaptive vs fixed**: :func:`_tree_policy_sim` replays one
      recorded bimodal acceptance trace under the adaptive controller
      and under every fixed shape in its static set; adaptive must beat
      them all on emitted-tokens-per-modeled-cost.

    Returns the ``tree_*`` fields of the spec record."""
    import numpy as np

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import ServingEngine
    from apex_tpu.spec import (AdaptiveSpecController, NGramTreeDrafter,
                               PagedModelDrafter)

    depth, branching = 4, 2
    tree_out = np.asarray(deng.generate(
        params, jnp.asarray(prompt)[None], new_tokens,
        draft=NGramTreeDrafter(depth=depth, branching=branching)))
    tree_greedy_parity = bool((tree_out == want).all())
    tstats = deng.last_spec_stats
    cache_ok = deng.spec_tree_step._cache_size() == 1
    times = []
    for _ in range(passes):
        t0 = time.perf_counter()
        out = deng.generate(params, jnp.asarray(prompt)[None], new_tokens,
                            draft=NGramTreeDrafter(depth=depth,
                                                   branching=branching))
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    tps_tree = new_tokens / min(times)

    # churn with the drafter's KV as first-class paged-pool state: a
    # half-size draft model, its blocks drawn from the target pool
    dcfg = dict(cfg, hidden_size=max(cfg["hidden_size"] // 2, 32),
                num_layers=max(cfg["num_layers"] // 2, 1))
    dmodel = GPTModel(GPTConfig(**dcfg))
    dparams = dmodel.init(jr.PRNGKey(7))
    if cast is not None:
        dparams = jax.tree.map(lambda x: x.astype(cast), dparams)
    pdraft = PagedModelDrafter(dmodel, dparams, depth=depth,
                               branching=branching)
    teng = ServingEngine(model, num_slots=slots, block_size=block,
                         prefill_chunk=chunk, cache_dtype=cast)
    done = teng.serve(params, trace(), telemetry=False, draft=pdraft)
    tree_churn_parity = all(list(r.tokens) == base_tokens[r.rid]
                            for r in done)
    cache_ok = cache_ok and teng.spec_tree_step._cache_size() == 1
    t0 = time.perf_counter()
    done = teng.serve(params, trace(), telemetry=False, draft=pdraft)
    churn_s = time.perf_counter() - t0
    total = sum(len(r.tokens) for r in done)
    tree_rounds = teng.last_stats.tree_rounds

    # adaptive (depth, branching) vs EVERY fixed shape in the static
    # set, replayed on one recorded bimodal acceptance trace
    choices = ((1, 1), (2, 1), (4, 1), (4, 2))
    adaptive_eff = _tree_policy_sim(
        adaptive=AdaptiveSpecController(choices, window=3))
    fixed_eff = [_tree_policy_sim(fixed=c) for c in choices]
    beats = all(adaptive_eff > e for e in fixed_eff)

    assert tree_greedy_parity and tree_churn_parity, \
        "tree-speculative decode diverged from the plain-decode baseline"
    assert cache_ok, "a tree-verify body re-traced (unstable avals?)"
    assert beats, (
        f"adaptive (depth, branching) did not beat every fixed shape on "
        f"the recorded bimodal trace: adaptive={adaptive_eff:.4f} vs "
        f"fixed={[round(e, 4) for e in fixed_eff]}")
    return dict(
        tree_spec_tokens_per_s_request=round(tps_tree, 1),
        tree_spec_tokens_per_s_churn=round(total / churn_s, 1),
        tree_spec_acceptance_rate=round(tstats.acceptance_rate, 4),
        tree_speedup=round(tps_tree / tps_base, 4),
        tree_depth=depth, tree_branching=branching,
        tree_nodes=branching * depth,
        tree_rounds=int(tree_rounds),
        tree_greedy_parity=tree_greedy_parity,
        tree_churn_parity=bool(tree_churn_parity),
        drafter_pool_blocks=int(pdraft.peak_blocks),
        adaptive_efficiency=round(adaptive_eff, 4),
        fixed_k_efficiency=[round(e, 4) for e in fixed_eff],
        adaptive_beats_fixed=bool(beats),
    )


def _tree_policy_sim(*, adaptive=None, fixed=None, streams=8, tokens=64,
                     p_easy=0.9, p_hard=0.1, overhead_rows=8.0, seed=11):
    """Replay one RECORDED bimodal acceptance trace — half the streams
    easy (per-row acceptance ``p_easy``), half hard (``p_hard``), draws
    fixed by ``seed`` — under a (depth, branching) policy, and score
    emitted tokens per MODELED verify cost. A round costs its verify
    rows (``branching*depth + 1``) plus ``overhead_rows``, the weight-
    streaming floor a decode dispatch pays regardless of row count;
    that floor is what makes depth pay on easy streams while wasted
    rows still hurt on hard ones, so neither a fixed-shallow nor a
    fixed-deep shape can win both halves. Pass ``adaptive=`` (an
    :class:`~apex_tpu.spec.AdaptiveSpecController`, queried and fed per
    round exactly like the serve loop does) or ``fixed=(depth,
    branching)``. Returns ``emitted / cost``."""
    import numpy as np

    emitted_total, cost = 0, 0.0
    for s in range(streams):
        p = p_easy if s % 2 == 0 else p_hard
        srng = np.random.RandomState(seed * 1000 + s)
        got = 0
        while got < tokens:
            d, b = adaptive.choice(s) if adaptive is not None else fixed
            # level 0 hedges: the first accepted branch (if any)
            # continues as a chain — the DraftTree acceptance shape
            accepted = 0
            if (srng.random_sample(b) < p).any():
                accepted = 1
                for _ in range(d - 1):
                    if srng.random_sample() >= p:
                        break
                    accepted += 1
            got += accepted + 1  # + the verify round's bonus token
            emitted_total += accepted + 1
            cost += b * d + 1 + overhead_rows
            if adaptive is not None:
                adaptive.note_round(s, accepted, d)
        if adaptive is not None:
            adaptive.release(s)
    return emitted_total / cost


def longseq_bias_main():
    """``python bench.py --longseq-bias`` — the long-sequence relative-
    bias leg: fwd+bwd flash attention with the IN-KERNEL bucketed bias
    (the ``BucketedBias`` operand: O(buckets·h) bias memory) against the
    r5 MATERIALIZED (h, s, s) operand (O(h·s²) — 1.5 GB fp32 at the TPU
    shape below), measuring tokens/s and the HBM high-water of each.

    Emits ONE ``longseq_bias`` record through the monitor schema and
    prints it as one JSON line; on TPU the record is ``status: "OK"``
    with both legs and the ratio, off-TPU an explicit ``status: "SKIP"``
    with a reason (smoke-scale CPU numbers ride along as finite fields,
    but a SKIP record claims no result — never nan in an OK line). HBM
    high-water comes from ``device.memory_stats()['peak_bytes_in_use']``;
    the peak is monotone per process, so the bucketed leg runs FIRST (its
    peak is its own) and the materialized leg's peak is read after —
    exact for the bucketed leg, a floor for the materialized one (which
    only understates the collapse being measured)."""
    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    from apex_tpu.ops.attention import BucketedBias, flash_attention

    if on_tpu:
        # T5-large-ish attention shape at long seq: the ISSUE's 1.6 GB
        # example (s=8192, h=6) with head_dim 128 (MXU lanes)
        b, s, h, d, nb, passes, iters = 1, 8192, 6, 128, 32, 3, 5
    else:  # smoke scale; the record is SKIP either way
        b, s, h, d, nb, passes, iters = 1, 256, 2, 64, 16, 2, 1
    causal = False  # the T5 ENCODER case (bidirectional buckets)
    maxd = 128

    key = jr.PRNGKey(0)
    q = jr.normal(key, (b, s, h, d), jnp.bfloat16)
    k = jr.normal(jr.fold_in(key, 1), (b, s, h, d), jnp.bfloat16)
    v = jr.normal(jr.fold_in(key, 2), (b, s, h, d), jnp.bfloat16)
    table = jr.normal(jr.fold_in(key, 3), (nb, h), jnp.float32) * 0.3

    def bucketed_step(q, k, v, t):
        o = flash_attention(q, k, v, causal=causal, layout="bshd",
                            bias=BucketedBias(t, True, maxd))
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def materialized_step(q, k, v, bias_arr):
        o = flash_attention(q, k, v, causal=causal, layout="bshd",
                            bias=bias_arr)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def time_leg(fn, *args):
        g = jax.jit(jax.grad(fn, argnums=(0, 1, 2, 3)))
        out = g(*args)  # compile+warm
        jax.block_until_ready(out)
        times = []
        for _ in range(passes):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(*args)
            jax.block_until_ready(out)
            times.append((time.perf_counter() - t0) / iters)
        return times

    def peak_mb():
        if not on_tpu:
            return None
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return None if peak is None else round(peak / 2 ** 20, 1)

    # bucketed leg FIRST: the process peak after it is ITS high-water
    bt = time_leg(bucketed_step, q, k, v, table)
    peak_bucketed = peak_mb()
    # materialized baseline: the (h, s, s) fp32 array the r5 path fed the
    # kernels (built outside the timed loop, as the model did per stack)
    bias_arr = BucketedBias(table, True, maxd).materialize(s, s)
    jax.block_until_ready(bias_arr)
    mt = time_leg(materialized_step, q, k, v, bias_arr)
    peak_materialized = peak_mb()

    tokens_per_s = b * s / min(bt)
    tokens_mat = b * s / min(mt)
    spread = (max(bt) - min(bt)) / min(bt)
    skip = lambda r: ("skipped", r)  # noqa: E731
    fields = dict(
        tokens_per_s=round(tokens_per_s, 1),
        tokens_per_s_materialized=round(tokens_mat, 1),
        vs_materialized=round(tokens_per_s / tokens_mat, 4),
        bias_bytes=int(nb * h * 4),
        bias_bytes_materialized=int(h * s * s * 4),
        seq=s, batch=b, heads=h, head_dim=d, num_buckets=nb,
        causal=causal, spread_pct=round(spread * 100, 2),
        pass_times_ms=[round(t * 1e3, 2) for t in bt],
        backend=jax.default_backend(),
    )
    no_stats = "device memory_stats unavailable on this backend"
    fields["hbm_peak_mb"] = (peak_bucketed if peak_bucketed is not None
                             else skip(no_stats))
    fields["hbm_peak_materialized_mb"] = (
        peak_materialized if peak_materialized is not None
        else skip(no_stats))
    if on_tpu:
        status = "OK"
    else:
        reason = (f"long-seq bias HBM/throughput is a TPU measurement; "
                  f"this is a {jax.default_backend()} smoke run at s={s}")
        fields["reason"] = reason
        status = "SKIP"

    if monitor.enabled():
        record = monitor.get_registry().emit_longseq_bias(status, **fields)
    else:  # sink-less registry: same construction+honesty path, no file
        record = monitor.MetricsRegistry().emit_longseq_bias(
            status, **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(
            f"longseq-bias bench record failed validation: {errors}")
    print(json.dumps(record))


def tp_overlap_main():
    """``python bench.py --tp-overlap`` — overlapped vs blocking TP
    boundary collectives on the flagship GPT block stack: one jitted
    fwd+bwd (loss + grads + SP grad sync) per impl under ``shard_map``
    on a tp-only mesh, tokens/s from min-of-passes with ``spread_pct``
    as the noise bar (the training bench's accounting).

    Emits ONE ``tp_overlap`` record through the monitor schema and
    prints it as one JSON line. ``status: "OK"`` requires a real
    multichip TPU (the overlap claim is an ICI-latency measurement);
    off-TPU the leg still runs end to end at smoke scale on a virtual
    8-device CPU mesh — the dryrun harness's recipe, with the
    device-count flag set here BEFORE jax initializes its backend — and
    the record is an explicit ``SKIP(reason)`` with the smoke numbers
    riding along as finite fields. A host with fewer than 2 usable
    devices emits SKIP without measurements. Never nan in an OK line."""
    # must precede the first backend query: the CPU platform only grows
    # virtual devices if the flag is set pre-initialization
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.models.gpt import shard_params_for_tp
    from apex_tpu.parallel import mesh as mesh_lib

    n = jax.device_count()
    tp = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 0)

    def emit(status, **fields):
        if monitor.enabled():
            record = monitor.get_registry().emit_tp_overlap(status, **fields)
        else:  # sink-less registry: same construction+honesty path
            record = monitor.MetricsRegistry().emit_tp_overlap(
                status, **fields)
        errors = monitor.validate(record)
        if errors:
            raise ValueError(
                f"tp-overlap bench record failed validation: {errors}")
        print(json.dumps(record))

    if tp < 2:
        reason = (f"tp overlap needs >= 2 devices on one axis; this "
                  f"{jax.default_backend()} host exposes {n}")
        emit("SKIP", reason=reason, backend=jax.default_backend())
        return

    if on_tpu:
        # flagship-block scale at tp: head_dim 128, SP on (the production
        # pairing — boundary collectives on every linear, fwd and bwd)
        kw = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                  num_layers=12, num_heads=8, attention_impl="flash",
                  remat=False, scan_layers=False)
        batch, seq, iters, passes = 8, 1024, 10, 3
        cast = jnp.bfloat16
    else:  # smoke scale on the virtual mesh; the record is SKIP anyway
        kw = dict(vocab_size=128, max_seq_len=64, hidden_size=64,
                  num_layers=2, num_heads=4, attention_impl="flash")
        batch, seq, iters, passes = 2, 64, 2, 2
        cast = None

    cfg1 = GPTConfig(**kw, tp_size=1)
    params1 = GPTModel(cfg1).init(jr.PRNGKey(0))
    if cast is not None:
        params1 = jax.tree.map(lambda x: x.astype(cast), params1)
    sharded = shard_params_for_tp(params1, tp, cfg1)
    specs = jax.tree.map(lambda _: P("tp"), sharded)
    mesh = mesh_lib.make_mesh(tensor_model_parallel_size=tp,
                              devices=jax.devices()[:tp])
    toks = jr.randint(jr.PRNGKey(1), (batch, seq), 0, kw["vocab_size"])
    tgts = jr.randint(jr.PRNGKey(2), (batch, seq), 0, kw["vocab_size"])

    def measure(overlap):
        # the ParallelPlan spelling (ISSUE 12): one validated object
        # instead of three loose kwargs
        from apex_tpu.plan import ParallelPlan
        model = GPTModel(GPTConfig(**kw, plan=ParallelPlan(
            tp=tp, sequence_parallel=True, tp_overlap=overlap)))

        def run(p, t, g):
            loss, grads = jax.value_and_grad(model.loss_fn)(
                jax.tree.map(lambda x: x[0], p), t, g)
            grads = model.sp_grad_sync(grads)
            return loss, jax.tree.map(lambda x: x[None], grads)

        step = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs)))
        loss, grads = step(sharded, toks, tgts)  # compile+warm
        float(loss)
        times = []
        for _ in range(passes):
            t0 = time.perf_counter()
            for _ in range(iters):
                loss, grads = step(sharded, toks, tgts)
            float(loss)  # host fetch syncs the dependent chain
            times.append((time.perf_counter() - t0) / iters)
        return batch * seq / min(times), times

    tps_overlap, pass_times = measure(True)
    tps_blocking, pass_times_b = measure(False)
    # spread over BOTH runs: vs_blocking is a ratio, so noise in the
    # blocking denominator moves the claim exactly as much as noise in
    # the overlapped numerator
    spread = (max(pass_times) - min(pass_times)) / min(pass_times)
    spread_b = (max(pass_times_b) - min(pass_times_b)) / min(pass_times_b)

    fields = dict(
        tokens_per_s=round(tps_overlap, 1),
        tokens_per_s_blocking=round(tps_blocking, 1),
        vs_blocking=round(tps_overlap / tps_blocking, 4),
        tp=tp, batch=batch, seq=seq, sequence_parallel=True,
        spread_pct=round(spread * 100, 2),
        spread_pct_blocking=round(spread_b * 100, 2),
        pass_times_ms=[round(t * 1e3, 2) for t in pass_times],
        pass_times_blocking_ms=[round(t * 1e3, 2) for t in pass_times_b],
        config=kw, backend=jax.default_backend(),
    )
    if on_tpu:
        status = "OK"
    else:
        reason = (f"tp-overlap speedup is an ICI-latency measurement; "
                  f"this is a {jax.default_backend()} smoke run on a "
                  f"virtual {n}-device mesh (tp={tp})")
        fields["reason"] = reason
        status = "SKIP"
    emit(status, **fields)


def profile_main(argv=None):
    """``python bench.py --profile [--logdir D] [--costdb F]`` — the
    step-anatomy leg: run the flagship train step with fwd_bwd/optimizer
    spans under a ``jax.profiler`` capture, fuse the span stream with the
    device trace (``prof.trace_reader.step_anatomy``), write the merged
    host+device timeline and the calibrated CostDB artifact
    (``prof.calibrate``), and emit ONE ``profile`` monitor record.

    On TPU the chrome trace carries per-HLO device events, the anatomy
    percentages are real and the record is ``status: "OK"``; off-TPU the
    trace is host-only (no XLA Ops track), so the record is an explicit
    ``status: "SKIP"`` with the smoke wall-times riding along and every
    device-derived metric an explicit skip object — never nan in an OK
    line. Span/trace/anatomy/CostDB *math* is tier-1-tested on synthetic
    fixtures; this leg is the real-capture path."""
    import sys

    from apex_tpu.monitor import report as monitor_report

    argv = list(sys.argv[1:] if argv is None else argv)

    def _opt(flag, default):
        return argv[argv.index(flag) + 1] if flag in argv else default

    logdir = _opt("--logdir", "/tmp/apex_tpu_profile")
    os.makedirs(logdir, exist_ok=True)
    costdb_path = _opt("--costdb", os.path.join(logdir, "costdb.json"))

    on_tpu = jax.default_backend() == "tpu"
    # spans need a live registry at TRACE time (scope names bake into the
    # compiled program's op names); respect APEX_TPU_MONITOR, else stream
    # next to the trace
    reg = monitor.enable_from_env()
    if reg is None:
        stream_path = os.path.join(logdir, "events.jsonl")
        monitor.enable(stream_path)
    else:
        stream_path = os.environ["APEX_TPU_MONITOR"]

    import optax

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.prof import calibrate, cost_analysis, trace
    from apex_tpu.prof import trace_reader

    if on_tpu:
        cfg = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                   num_layers=12, num_heads=8, tp_size=1, remat=False,
                   attention_impl="flash", scan_layers=False)
        batch, seq, steps = 20, 1024, 5
        cast = jnp.bfloat16
    else:  # smoke scale; the record is SKIP either way (host-only trace)
        cfg = dict(vocab_size=256, max_seq_len=64, hidden_size=64,
                   num_layers=2, num_heads=4, tp_size=1, remat=False,
                   attention_impl="flash")
        batch, seq, steps = 2, 64, 3
        cast = None

    model = GPTModel(GPTConfig(**cfg))
    params = model.init(jr.PRNGKey(0))
    if cast is not None:
        params = jax.tree.map(lambda x: x.astype(cast), params)
    opt = fused_adam(learning_rate=1e-4)
    opt_state = opt.init(params)
    tokens = jr.randint(jr.PRNGKey(1), (batch, seq), 0, cfg["vocab_size"])
    targets = jr.randint(jr.PRNGKey(2), (batch, seq), 0, cfg["vocab_size"])

    def train_step(params, opt_state, tokens, targets):
        # traced spans: fwd_bwd / optimizer scope every HLO they cover —
        # the join key the anatomy table and CostDB calibration read back
        # out of the device trace
        with monitor.span("fwd_bwd"):
            loss, grads = jax.value_and_grad(model.loss_fn)(
                params, tokens, targets)
        with monitor.span("optimizer"):
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))

    monitor.emit_meta(
        device_kind=jax.devices()[0].device_kind if on_tpu else "cpu",
        backend=jax.default_backend(),
        model_flops_per_token=model_flops_per_token(cfg, seq),
        batch=batch, seq=seq, config=cfg,
        metric="gpt_step_anatomy_profile",
    )
    # XLA's own prediction for the whole program — the costdb's
    # achieved-vs-predicted reference line. TPU only: the CPU backend
    # reports no optimal_seconds, so the smoke run would pay a second
    # full compile for a None
    pred = None
    if on_tpu:
        ca = cost_analysis(train_step, params, opt_state, tokens, targets)
        if ca.get("flops", 0) > 0 and ca.get("optimal_seconds", 0) > 0:
            pred = ca["flops"] / ca["optimal_seconds"]

    # compile+warm OUTSIDE the capture (scope names are program
    # properties; the capture only needs executions)
    params, opt_state, loss = step(params, opt_state, tokens, targets)
    float(loss)
    with trace(logdir):
        for i in range(steps):
            with monitor.span("step", step=i):
                params, opt_state, loss = step(params, opt_state, tokens,
                                               targets)
                float(loss)  # block INSIDE the span: wall time is honest

    records = monitor_report.read_records(open(stream_path))
    spans = [r for r in records if r.get("kind") == "span"]
    events = trace_reader.read_trace(logdir)
    rows = trace_reader.step_anatomy(spans, events)
    timeline_path = os.path.join(logdir, "merged_trace.json")
    trace_reader.write_merged_timeline(timeline_path, spans, events)
    db = calibrate.build_costdb(
        records, events,
        device_kind=jax.devices()[0].device_kind if on_tpu else "cpu",
        backend=jax.default_backend(), predicted_flops_per_s=pred)
    calibrate.write_costdb(costdb_path, db)

    walls = [s["dur_ns"] / 1e9 for s in
             trace_reader.host_step_spans(spans)]
    fields = dict(
        steps=len(walls), span_records=len(spans),
        step_wall_ms=round(sum(walls) / len(walls) * 1e3, 3),
        tokens_per_s=round(batch * seq / min(walls), 1),
        costdb_collective_rows=sum(len(v) for v in
                                   db["collectives"].values()),
        costdb_gemm_classes=len(db["gemms"]),
        costdb_path=costdb_path, timeline_path=timeline_path,
        trace_dir=logdir, config=cfg, backend=jax.default_backend(),
    )

    def mean_pct(key):
        return round(sum(r[key] for r in rows) / len(rows), 2)

    if rows and on_tpu:
        fields.update(compute_pct=mean_pct("compute_pct"),
                      collective_exposed_pct=mean_pct(
                          "collective_exposed_pct"),
                      bubble_pct=mean_pct("bubble_pct"),
                      host_gap_pct=mean_pct("host_gap_pct"))
        status = "OK"
    else:
        reason = ("step anatomy needs per-HLO device events; this "
                  f"{jax.default_backend()} trace is host-only"
                  if not rows else
                  "anatomy percentages are a TPU measurement; this is a "
                  f"{jax.default_backend()} smoke run")
        for k in ("compute_pct", "collective_exposed_pct", "bubble_pct",
                  "host_gap_pct"):
            fields[k] = ("skipped", reason)
        fields["reason"] = reason
        status = "SKIP"

    record = monitor.get_registry().emit_profile(status, **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(f"profile bench record failed validation: {errors}")
    print(json.dumps(record))


def pipeline_main():
    """``python bench.py --pipeline`` — the pipeline-schedule leg: the
    zero-bubble schedule (``GPTConfig(pp_schedule="zb")``) vs the
    autodiff 1f1b baseline on the flagship GPT blocks through
    ``GPTPipeline`` at pp >= 2 — one jitted fwd+bwd per schedule under
    ``shard_map``, tokens/s from min-of-passes with ``spread_pct`` as the
    noise bar (the training bench's accounting), plus bubble %:
    MEASURED by ``prof.trace_reader.step_anatomy`` on a real TPU trace,
    and from the trace-time unit-cost geometry
    (``monitor.pipeline_cost_model``) everywhere. Both jitted paths are
    witnessed recompile-free across schedule-geometry reuse
    (``jit_cache_ok``: fresh data through the same geometry keeps the
    jit cache at 1).

    Emits ONE ``pipeline`` record through the monitor schema and prints
    it as one JSON line. ``status: "OK"`` requires a real multichip TPU;
    off-TPU the leg still runs end to end at smoke scale on a virtual
    8-device CPU mesh and the record is an explicit ``SKIP(reason)`` with
    the smoke numbers and geometry riding along. Never nan in an OK
    line."""
    # must precede the first backend query: the CPU platform only grows
    # virtual devices if the flag is set pre-initialization
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.pipeline_parallel import GPTPipeline

    n = jax.device_count()
    pp = 4 if (n % 4 == 0 and n >= 4) else (2 if n % 2 == 0 else 0)

    def emit(status, **fields):
        if monitor.enabled():
            record = monitor.get_registry().emit_pipeline(status, **fields)
        else:  # sink-less registry: same construction+honesty path
            record = monitor.MetricsRegistry().emit_pipeline(
                status, **fields)
        errors = monitor.validate(record)
        if errors:
            raise ValueError(
                f"pipeline bench record failed validation: {errors}")
        print(json.dumps(record))

    if pp < 2:
        emit("SKIP", reason=(f"a pipeline needs >= 2 stages; this "
                             f"{jax.default_backend()} host exposes {n} "
                             "device(s)"),
             backend=jax.default_backend())
        return

    if on_tpu:
        kw = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                  num_layers=12, num_heads=8, attention_impl="flash",
                  remat=True, scan_layers=False)
        M, b, s, iters, passes = 2 * pp, 4, 1024, 10, 3
        cast = jnp.bfloat16
    else:  # smoke scale on the virtual mesh; the record is SKIP anyway
        kw = dict(vocab_size=128, max_seq_len=64, hidden_size=64,
                  num_layers=pp * 2, num_heads=4, attention_impl="flash")
        M, b, s, iters, passes = 2 * pp, 2, 32, 2, 2
        cast = None

    # the ParallelPlan spelling (ISSUE 12); the measured schedule is
    # still selected per leg below (zb vs the 1f1b baseline)
    from apex_tpu.plan import ParallelPlan
    model = GPTModel(GPTConfig(**kw, plan=ParallelPlan(
        pp=pp, pp_schedule="zb")))
    params = model.init(jr.PRNGKey(0))
    if cast is not None:
        params = jax.tree.map(
            lambda x: x.astype(cast)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    pipe = GPTPipeline(model, pp=pp)
    part = pipe.partition(params)
    specs = pipe.param_specs(part)
    mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=pp,
                              devices=jax.devices()[:pp])
    toks = jr.randint(jr.PRNGKey(1), (M, b, s), 0, kw["vocab_size"])
    tgts = jr.randint(jr.PRNGKey(2), (M, b, s), 0, kw["vocab_size"])

    def build_step(schedule):
        def run(p, t, g):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, grads = pipe.loss_and_grads(lp, t, g, schedule=schedule)
            grads["stages"] = jax.tree.map(lambda x: x[None],
                                           grads["stages"])
            return loss, grads

        return jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs)))

    def measure(schedule):
        step = build_step(schedule)
        loss, _ = step(part, toks, tgts)  # compile+warm
        float(loss)
        # geometry-reuse witness: fresh data, same schedule geometry —
        # the jit cache must stay at 1 (no retrace per step)
        loss, _ = step(part, toks + 1, tgts)
        float(loss)
        cache_ok = step._cache_size() == 1
        times = []
        for _ in range(passes):
            t0 = time.perf_counter()
            for _ in range(iters):
                loss, _ = step(part, toks, tgts)
            float(loss)  # host fetch syncs the dependent chain
            times.append((time.perf_counter() - t0) / iters)
        return M * b * s / min(times), times, cache_ok, step

    def measured_bubble(step):
        """Mean step_anatomy bubble % from a real device trace (TPU); a
        ('skipped', reason) marker anywhere that is unavailable.
        step_anatomy pairs device windows with HOST step spans, so each
        traced execution is stamped with one (blocking inside the span —
        the wall time is honest, same contract as profile_main's)."""
        if not on_tpu:
            return ("skipped", "step_anatomy needs a TPU device trace; "
                               "off-TPU the chrome trace is host-only")
        import tempfile

        from apex_tpu.prof import trace_reader
        try:
            spans = []
            with tempfile.TemporaryDirectory() as logdir:
                jax.profiler.start_trace(logdir)
                for i in range(3):
                    t0 = time.monotonic_ns()
                    loss, _ = step(part, toks, tgts)
                    float(loss)  # block INSIDE the span window
                    spans.append({"kind": "span", "name": "step",
                                  "step": i, "t0_ns": t0,
                                  "dur_ns": time.monotonic_ns() - t0})
                jax.profiler.stop_trace()
                events = trace_reader.read_trace(logdir)
                rows = trace_reader.step_anatomy(spans, events)
            vals = [r["bubble_pct"] for r in rows
                    if isinstance(r.get("bubble_pct"), (int, float))]
            if not vals:
                return ("skipped", "trace carried no per-step device rows")
            return round(sum(vals) / len(vals), 2)
        except Exception as e:  # noqa: BLE001 — a broken trace must not
            return ("skipped", f"trace capture failed: {e}")  # kill the leg

    tps_zb, pass_times, cache_zb, step_zb = measure("zb")
    tps_1f1b, pass_times_b, cache_1f1b, step_1f1b = measure("1f1b")
    spread = (max(pass_times) - min(pass_times)) / min(pass_times)
    spread_b = (max(pass_times_b) - min(pass_times_b)) / min(pass_times_b)
    geo_zb = monitor.pipeline_cost_model(M, pp, 1, schedule="zb")
    geo_1f1b = monitor.pipeline_cost_model(M, pp, 1, schedule="1f1b")
    # the schedule's own traffic accounting: fwd ticks x one microbatch
    # activation (both directions add the dX sweep's mirror of it)
    act_bytes = b * s * kw["hidden_size"] * (2 if cast else 4)
    fields = dict(
        schedule="zb", pipeline_size=pp, virtual_chunks=1,
        num_microbatches=M, overlap_p2p=False,
        tokens_per_s=round(tps_zb, 1),
        tokens_per_s_1f1b=round(tps_1f1b, 1),
        vs_1f1b=round(tps_zb / tps_1f1b, 4),
        bubble_pct=measured_bubble(step_zb),
        bubble_pct_1f1b=measured_bubble(step_1f1b),
        bubble_pct_geometry=round(100 * geo_zb["bubble_fraction"], 2),
        bubble_pct_1f1b_geometry=round(
            100 * geo_1f1b["bubble_fraction"], 2),
        p2p_bytes_per_step=act_bytes * geo_zb["fwd_ticks"] * 2,
        jit_cache_ok=bool(cache_zb and cache_1f1b),
        spread_pct=round(spread * 100, 2),
        spread_pct_1f1b=round(spread_b * 100, 2),
        pass_times_ms=[round(t * 1e3, 2) for t in pass_times],
        pass_times_1f1b_ms=[round(t * 1e3, 2) for t in pass_times_b],
        config=kw, backend=jax.default_backend(),
    )
    if on_tpu:
        status = "OK"
    else:
        fields["reason"] = (
            "pipeline-schedule speedup is an ICI/bubble measurement; "
            f"this is a {jax.default_backend()} smoke run on a virtual "
            f"{n}-device mesh (pp={pp})")
        status = "SKIP"
    emit(status, **fields)


def plan_main(argv=None):
    """``python bench.py --plan [--costdb F] [--chips N]`` — the
    auto-parallelism planner leg (ISSUE 12): search → pick → measure.

    **Search**: enumerate the feasible plan lattice for ``--chips``
    (default: every visible device) over the flagship workload, price
    every candidate from the CostDB (``--costdb`` names a measured
    artifact from ``bench.py --profile --costdb``; without one, a
    uniform reference rate is used and every key is flagged
    uncalibrated), and rank by predicted step time
    (:func:`apex_tpu.plan.search.search_plans`).

    **Pick**: the chosen plan is JXP-gated in-process — the
    ``planned_gpt_step`` entrypoint traces it and checks donation +
    the schedule/overlap contracts its knobs engage (``lint_ok``); the
    planner can never ship a plan that violates a shipped invariant.

    **Measure**: the chosen plan's per-chip step program (the exact
    program the pricing traced, instantiated with real operands) is
    timed min-of-passes, and ``predicted_vs_measured_err_pct`` is
    recorded — the series ``tools/bench_history.py`` gates for drift.
    The schedule's warmup/drain enters *predicted* through the
    ``pipeline_cost_model`` factor while the measured per-chip program
    carries only the useful work, so the error series includes the
    schedule-model term by construction; DRIFT is what the gate
    watches. Memory is measured too (apexmem): the chosen plan's
    donation-aware liveness bound (``predicted_peak_hbm_mb``) is
    compared against ``memory_stats()['peak_bytes_in_use']`` into
    ``predicted_vs_measured_hbm_err_pct``, a second gated series. On
    TPU the record is ``status: "OK"``; off-TPU the measured halves
    ride as explicit skip objects (never nan in an OK line) with
    ``smoke_step_ms`` as the finite plumbing witness that the full
    search→pick→measure loop ran.
    """
    import sys

    # must precede the first backend query: the CPU platform only grows
    # virtual devices if the flag is set pre-initialization
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()

    from apex_tpu.lint import entrypoints as lint_eps
    from apex_tpu.plan import Workload, plan_record_fields, search_plans
    from apex_tpu.prof.calibrate import validate_costdb

    argv = list(sys.argv[1:] if argv is None else argv)

    def _opt(flag, default):
        return argv[argv.index(flag) + 1] if flag in argv else default

    chips = int(_opt("--chips", jax.device_count()))
    costdb_path = _opt("--costdb", None)

    if on_tpu:
        # the flagship train-bench dims (bench `main()`'s config) at a
        # searchable batch geometry
        w = Workload(hidden_size=1024, num_layers=12, vocab_size=32768,
                     seq=1024, global_batch=16, micro_batch=2,
                     dtype_bytes=2, remat=False)
        iters, passes = 10, 3
    else:  # smoke scale; the record is SKIP either way
        w = Workload(hidden_size=64, ffn_hidden_size=256, num_layers=4,
                     vocab_size=256, seq=64, global_batch=8,
                     micro_batch=1, dtype_bytes=4, remat=False)
        iters, passes = 2, 2

    if costdb_path:
        with open(costdb_path) as fh:
            db = json.load(fh)
        errors = validate_costdb(db)
        if errors:
            raise ValueError(f"{costdb_path} is not a valid costdb: "
                             f"{errors}")
        source = costdb_path
    else:
        # no measured CostDB: the empty table makes every key a flagged
        # blind spot priced at the uniform reference floors — the
        # ranking reflects geometry alone, labeled, never silent
        db = {"schema": 1, "kind": "costdb", "collectives": {},
              "gemms": {}}
        source = "uniform-reference"
    # blind spots price at the SLOWEST measured rate (never 0 ms): a
    # plan must not win because its dominant traffic was never measured;
    # the memory column comes from the donation-aware LIVENESS walk of
    # each candidate's traced step (apexmem), with >10% closed-form
    # disagreement surfaced as a memory_model[...] honesty flag
    from apex_tpu.plan import conservative_defaults
    result = search_plans(chips, w, db, memory_source="liveness",
                          **conservative_defaults(db))
    best = result.best

    # JXP-gate the chosen plan through the registered entrypoint — the
    # same contracts `python -m apex_tpu.lint --jaxpr` enforces
    os.environ["APEX_TPU_PLAN"] = json.dumps(best.plan.to_json())
    try:
        findings, _cost = lint_eps.check("planned_gpt_step")
        lint_ok = not findings
    finally:
        os.environ.pop("APEX_TPU_PLAN", None)

    # measure the priced per-chip program (real operands, min-of-passes)
    from apex_tpu.plan import build_plan_step
    fn, sds_args = build_plan_step(best.plan, w)
    step = jax.jit(fn, donate_argnums=(0,))
    args = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), sds_args)
    params, x, tgt = args
    params, loss = step(params, x, tgt)  # compile+warm
    float(loss)
    times = []
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, loss = step(params, x, tgt)
        float(loss)  # host fetch syncs the dependent chain
        times.append((time.perf_counter() - t0) / iters)
    measured_ms = min(times) * 1e3

    # apexmem: predicted peak HBM (the liveness bound of the measured
    # program, per chip) vs the device allocator's high-water. The
    # measured side exists only on TPU with memory_stats(); off-TPU it
    # rides as explicit skip objects — never nan in an OK line.
    from apex_tpu.plan import liveness_memory
    predicted_peak_mb = round(liveness_memory(best.plan, w).total
                              / 2 ** 20, 2)
    measured_peak_mb = None
    if on_tpu:
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            measured_peak_mb = round(peak / 2 ** 20, 2)

    skip_reason = (None if on_tpu else
                   f"plan step-time is a TPU measurement; this is a "
                   f"{jax.default_backend()} smoke run on a virtual "
                   f"{jax.device_count()}-device mesh")
    fields = plan_record_fields(
        result, costdb_source=source,
        measured_step_ms=measured_ms if on_tpu else None,
        skip_reason=skip_reason)
    no_stats = "device memory_stats unavailable on this backend"
    skip = lambda r: ("skipped", r)  # noqa: E731
    if measured_peak_mb is not None:
        hbm_err = (100.0 * abs(predicted_peak_mb - measured_peak_mb)
                   / measured_peak_mb)
        fields["measured_peak_hbm_mb"] = measured_peak_mb
        fields["predicted_vs_measured_hbm_err_pct"] = round(hbm_err, 3)
    else:
        reason = skip_reason or no_stats
        fields["measured_peak_hbm_mb"] = skip(reason)
        fields["predicted_vs_measured_hbm_err_pct"] = skip(reason)
    fields.update(
        predicted_peak_hbm_mb=predicted_peak_mb,
        lint_ok=bool(lint_ok),
        smoke_step_ms=round(measured_ms, 4),
        config={"hidden_size": w.hidden_size, "num_layers": w.num_layers,
                "vocab_size": w.vocab_size, "seq": w.seq,
                "global_batch": w.global_batch,
                "micro_batch": w.micro_batch, "remat": w.remat},
        backend=jax.default_backend(),
    )
    if on_tpu:
        status = "OK"
    else:
        fields["reason"] = skip_reason
        status = "SKIP"

    if monitor.enabled():
        record = monitor.get_registry().emit_plan(status, **fields)
    else:  # sink-less registry: same construction+honesty path, no file
        record = monitor.MetricsRegistry().emit_plan(status, **fields)
    errors = monitor.validate(record)
    if errors:
        raise ValueError(f"plan bench record failed validation: {errors}")
    print(json.dumps(record))


def ckpt_main():
    """``python bench.py --ckpt`` — the elastic-checkpoint leg: a GPT
    train loop with dp-sharded ZeRO Adam, checkpointed through
    ``apex_tpu.ckpt.ZeroCheckpointManager`` async saves. Measures the
    steady clean step (min-of-passes), the mean step while a save is in
    flight (``save_overhead_pct`` = the extra wall per step a saving
    run pays — the lower-is-better series ``tools/bench_history.py``
    gates), the snapshot (on-path) vs write (background) split, restore
    time, and runs BOTH acceptance witnesses in-process: same-dp
    restore bitwise (masters/m/v identical) and elastic dp-resize row
    parity. One ``ckpt`` record; ``status: "OK"`` requires a real TPU,
    off-TPU the leg runs at smoke scale on the virtual 8-device CPU
    mesh and the record is an explicit ``SKIP(reason)`` with the smoke
    numbers riding along. Never nan in an OK line."""
    import shutil
    import tempfile

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from apex_tpu import ckpt as ckpt_lib
    from apex_tpu.contrib.optimizers import distributed_fused_adam
    from apex_tpu.contrib.optimizers.distributed import gather_zero_state
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.parallel import mesh as mesh_lib

    def emit(status, **fields):
        if monitor.enabled():
            record = monitor.get_registry().emit_ckpt(status, **fields)
        else:
            record = monitor.MetricsRegistry().emit_ckpt(status, **fields)
        errors = monitor.validate(record)
        if errors:
            raise ValueError(
                f"ckpt bench record failed validation: {errors}")
        print(json.dumps(record))

    dp = jax.device_count()
    if dp < 2:
        emit("SKIP", reason=(f"elastic ZeRO checkpointing needs dp >= 2; "
                             f"this {jax.default_backend()} host exposes "
                             f"{dp} device(s)"),
             backend=jax.default_backend())
        return

    if on_tpu:
        kw = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                  num_layers=12, num_heads=8, attention_impl="flash",
                  remat=False, scan_layers=False)
        b, s, iters, passes, save_every = 2 * dp, 1024, 10, 3, 4
    else:  # smoke scale; the record is SKIP anyway
        kw = dict(vocab_size=256, max_seq_len=64, hidden_size=64,
                  num_layers=2, num_heads=4, attention_impl="flash")
        b, s, iters, passes, save_every = dp, 32, 4, 2, 2

    mesh = mesh_lib.make_mesh()
    model = GPTModel(GPTConfig(**kw))
    params = model.init(jr.PRNGKey(0))
    zopt = distributed_fused_adam(learning_rate=1e-3)
    toks = jr.randint(jr.PRNGKey(1), (b, s), 0, kw["vocab_size"])
    tgts = jr.randint(jr.PRNGKey(2), (b, s), 0, kw["vocab_size"])

    def zero_step(p, t, g, st):
        loss, grads = jax.value_and_grad(model.loss_fn)(p, t, g)
        updates, st = zopt.update(grads, st, p)
        return optax.apply_updates(p, updates), st, jax.lax.pmean(
            loss, "dp")

    step = jax.jit(mesh_lib.shard_map(
        zero_step, mesh=mesh, in_specs=(P(), P("dp"), P("dp"), P()),
        out_specs=(P(), P(), P())))
    zstate = mesh_lib.shard_map(lambda p: zopt.init(p), mesh=mesh,
                                in_specs=P(), out_specs=P())(params)
    params, zstate, loss = step(params, toks, tgts, zstate)  # compile
    float(loss)

    # clean steady-state step: min-of-passes (the training bench's rule)
    times = []
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, zstate, loss = step(params, toks, tgts, zstate)
        float(loss)
        times.append((time.perf_counter() - t0) / iters)
    step_ms = min(times) * 1e3
    spread = (max(times) - min(times)) / min(times)

    root = tempfile.mkdtemp(prefix="apex_tpu_ckpt_bench_")
    try:
        snapshot_ms = write_ms = None
        with ckpt_lib.ZeroCheckpointManager(root, max_to_keep=2) as mgr:
            # the saving pass: same step loop, one async save every
            # save_every steps — the snapshot is the only on-path part
            nsteps = iters * passes
            saves = 0
            t0 = time.perf_counter()
            for i in range(nsteps):
                params, zstate, loss = step(params, toks, tgts, zstate)
                if i % save_every == 0:
                    float(loss)  # the step really finished; snapshot
                    # BETWEEN steps, exactly the train-loop contract
                    g = gather_zero_state(zstate, mesh)
                    mgr.save(i, g, dp=dp, params=params, force=True)
                    saves += 1
            float(loss)
            # the clock stops BEFORE draining the final background
            # write: save_overhead_pct claims per-STEP overhead (the
            # snapshot is the only on-path part), and the last write's
            # drain is off-step disk time — folding it in would make
            # the lower-is-better gate track disk speed, not the saver
            step_saving_ms = (time.perf_counter() - t0) / nsteps * 1e3
            mgr.wait_until_finished()
            snapshot_ms = mgr.last_timings.get("snapshot_ms")
            write_ms = mgr.last_timings.get("write_ms")

            # the acceptance witnesses, measured on the live state
            g_final = gather_zero_state(zstate, mesh)
            final_dir = os.path.join(root, "final")
            manifest = ckpt_lib.save_zero_sharded(
                final_dir, g_final, dp=dp, params=params, step=nsteps)
            t0 = time.perf_counter()
            st_same, _ = ckpt_lib.load_zero_state(final_dir, params,
                                                  dp=dp)
            restore_ms = (time.perf_counter() - t0) * 1e3
            bitwise = all(
                np.array_equal(np.asarray(g_final.buffers[k]),
                               np.asarray(st_same.buffers[k]))
                for k in st_same.buffers)
            dp2 = dp // 2
            st_el, _ = ckpt_lib.load_zero_state(final_dir, params,
                                                dp=dp2)
            n_rows = manifest.n_chunks
            elastic = all(
                np.array_equal(np.asarray(g_final.buffers[k])[:n_rows],
                               np.asarray(st_el.buffers[k])[:n_rows])
                for k in st_el.buffers)
            bytes_written = sum(
                os.path.getsize(os.path.join(final_dir, f))
                for f in os.listdir(final_dir))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    fields = dict(
        save_overhead_pct=round(
            max(100.0 * (step_saving_ms - step_ms) / step_ms, 0.0), 2),
        step_ms=round(step_ms, 3),
        step_ms_saving=round(step_saving_ms, 3),
        snapshot_ms=(round(snapshot_ms, 3)
                     if isinstance(snapshot_ms, (int, float))
                     else ("skipped", "no async save landed")),
        write_ms=(round(write_ms, 3)
                  if isinstance(write_ms, (int, float))
                  else ("skipped", "no async save landed")),
        restore_ms=round(restore_ms, 3),
        bytes_written=int(bytes_written),
        steps=nsteps, saves=saves, save_every=save_every, dp=dp,
        async_save=True,
        bitwise_resume_ok=bool(bitwise),
        elastic_resume_ok=bool(elastic),
        manifest=manifest.summary(),
        spread_pct=round(spread * 100, 2),
        config=kw, backend=jax.default_backend(),
    )
    if not (bitwise and elastic):
        raise AssertionError(
            f"checkpoint resume witnesses failed: bitwise={bitwise} "
            f"elastic={elastic} — the ckpt record must not ship")
    if on_tpu:
        status = "OK"
    else:
        fields["reason"] = (
            "checkpoint save overhead is a device-transfer + disk "
            f"measurement; this is a {jax.default_backend()} smoke run "
            f"on a virtual {dp}-device mesh")
        status = "SKIP"
    if mgr.last_trace_id:  # join the record to its last save's
        fields["trace_id"] = mgr.last_trace_id  # ckpt_save_start/commit
    emit(status, **fields)
    mesh_lib.destroy_model_parallel()


def main():
    on_tpu = jax.default_backend() == "tpu"
    monitor.enable_from_env()  # APEX_TPU_MONITOR=<path> streams JSONL
    if on_tpu:
        # remat=False: the un-rematted step fits 16G since the
        # vocab-parallel CE stopped saving an fp32 softmax residual
        # (recompute-from-lse backward) — measured 75.3k vs 71.3k tok/s
        # against the previous mlp_only policy.
        # scan_layers=False: at 12 layers the unrolled program removes the
        # scan carry's copy/DUS overhead (measured +7%: 70.8k vs 66.0k
        # tok/s) for ~10s extra compile.
        # num_heads=8 (head_dim 128, not 16x64): the MXU contracts/emits
        # 128 lanes, so d=64 runs the attention kernels at half lane
        # utilization — measured 76.4k vs 98.2k tok/s (+28%) at identical
        # hidden/layers/params/FLOPs. Same hardware reasoning as
        # Llama-class models' head_dim=128.
        cfg = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                   num_layers=12, num_heads=8, tp_size=1, remat=False,
                   attention_impl="flash", scan_layers=False)
        # batch 20, re-probed after the in-kernel-delta backward landed:
        # same-process sweep measured b16 111.5k / b20 116.4k / b24 116.1k
        # tok/s (b16 won every sweep before it; b32 OOM-thrashes at 94k) —
        # the shorter prologue moved the knee up one notch.
        batch, seq, iters = 20, 1024, 20
    else:  # smoke-test scale for CPU runs
        cfg = dict(vocab_size=1024, max_seq_len=128, hidden_size=128,
                   num_layers=2, num_heads=4, tp_size=1, remat=False,
                   attention_impl="flash")
        batch, seq, iters = 2, 128, 3

    tokens = jr.randint(jr.PRNGKey(1), (batch, seq), 0, cfg["vocab_size"])
    targets = jr.randint(jr.PRNGKey(2), (batch, seq), 0, cfg["vocab_size"])

    # Donation is PINNED on, applied to BOTH impls (VERDICT r3 weak #7):
    # the probe that used to pick it could only coin-flip — r4 measured
    # the two settings at parity across repeated runs (115.6–116.7k tok/s
    # both ways; the historical "~5× donation cost through the tunnel" is
    # long gone) and shorter probe loops are noisier than any honest
    # decision margin. Donating is the memory-safer choice (params+opt
    # state update in place). Noise accounting (VERDICT r4 weak #3): the
    # HEADLINE is min-of-3 passes; spread_pct = (max-min)/min across the
    # passes is the per-run noise bar and the raw pass times ship in the
    # artifact. Through the tunnel a single transient stall can put ~1%
    # on one pass (BENCH_r04's 1.19%) while back-to-back clean passes
    # reproduce to ~0.1% — min-of-3 makes the headline insensitive to
    # which kind of run the driver caught.
    donate = True

    if monitor.enabled():
        monitor.emit_meta(
            device_kind=jax.devices()[0].device_kind if on_tpu else "cpu",
            backend=jax.default_backend(),
            model_flops_per_token=model_flops_per_token(cfg, seq),
            batch=batch, seq=seq, iters=iters, config=cfg,
            metric="gpt_medium_train_step_throughput",
        )

    results = {}
    pass_times = []
    for impl in ("baseline", "fused"):
        os.environ["APEX_TPU_PALLAS"] = "0" if impl == "baseline" else "1"
        step, params, opt_state = build(impl, cfg, donate)
        if impl == "fused":
            # only the fused (framework) passes are the headline; their
            # step records are what `monitor report` reproduces tokens/s from
            results[impl], pass_times = timeit(
                step, params, opt_state, tokens, targets, iters,
                return_passes=True, monitor_tokens=batch * seq)
        else:
            results[impl] = timeit(
                step, params, opt_state, tokens, targets, iters)
        del step, params, opt_state
    spread = (max(pass_times) - min(pass_times)) / min(pass_times)

    if results["baseline"] / results["fused"] > 3.0:
        # a >3x ratio has always been a transient tunnel stall in the
        # baseline pass (observed once: 12.5x), never a real kernel gap —
        # re-time the baseline and keep the faster (honest) measurement
        os.environ["APEX_TPU_PALLAS"] = "0"
        step, params, opt_state = build("baseline", cfg, donate)
        results["baseline"] = min(
            results["baseline"],
            timeit(step, params, opt_state, tokens, targets, iters))
        del step, params, opt_state

    tokens_per_s = batch * seq / results["fused"]
    vs_baseline = results["baseline"] / results["fused"]
    flops_per_s = model_flops_per_token(cfg, seq) * tokens_per_s
    peak = (monitor.spec_peak_flops(jax.devices()[0].device_kind)
            if on_tpu else None)
    result = {
        "metric": "gpt_medium_train_step_throughput",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
        "mfu": round(flops_per_s / peak, 4) if peak else None,
        "model_tflops": round(flops_per_s / 1e12, 2),
        "donated": donate,
        "spread_pct": round(spread * 100, 2),
        "pass_times_ms": [round(t * 1e3, 2) for t in pass_times],
    }
    # the artifact is schema-checked before it is printed: a nan/inf in a
    # bench result must crash the bench, never ship inside a success line
    errors = monitor.validate(result)
    if errors:
        raise ValueError(f"bench artifact failed validation: {errors}")
    if monitor.enabled():
        monitor.emit_event("bench_result", **result)
    print(json.dumps(result))


if __name__ == "__main__":
    import sys

    if "--profile" in sys.argv[1:]:
        profile_main([a for a in sys.argv[1:] if a != "--profile"])
    elif "--decode" in sys.argv[1:]:
        decode_main()
    elif "--serve" in sys.argv[1:]:
        if "--plan-serve" in sys.argv[1:]:
            plan_serve_main(sys.argv[1:])
        elif "--plan-tp" in sys.argv[1:]:
            tp_serve_main(sys.argv[1:])
        else:
            serve_main()
    elif "--longseq-bias" in sys.argv[1:]:
        longseq_bias_main()
    elif "--tp-overlap" in sys.argv[1:]:
        tp_overlap_main()
    elif "--pipeline" in sys.argv[1:]:
        pipeline_main()
    elif "--plan" in sys.argv[1:]:
        plan_main([a for a in sys.argv[1:] if a != "--plan"])
    elif "--ckpt" in sys.argv[1:]:
        ckpt_main()
    elif "--spec" in sys.argv[1:]:
        spec_main(tree="--tree" in sys.argv[1:])
    else:
        main()
