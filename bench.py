"""Flagship benchmark: GPT training-step throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The measured config is a GPT-small-class decoder (bf16 compute) doing a full
train step (loss + grad + FusedAdam update). ``vs_baseline`` compares the
framework's fused path (Pallas kernels + fused optimizer) against the same
model with every fused op forced to its plain-XLA composition and an unfused
optax adam — i.e. "apex_tpu vs plain JAX", the TPU analog of the reference's
"apex vs stock PyTorch" pitch (the reference publishes no numbers of its
own, SURVEY.md §6).
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import jax.random as jr


def build(impl: str, cfg_kwargs):
    import optax

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import fused_adam

    cfg = GPTConfig(**cfg_kwargs)
    model = GPTModel(cfg)
    params = model.init(jr.PRNGKey(0))
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    if impl == "fused":
        opt = fused_adam(learning_rate=1e-4)
    else:
        opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss_fn)(params, tokens, targets)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # NB: no donate_argnums — buffer donation through the remote-TPU tunnel
    # both defeats block_until_ready (async completion reported early) and
    # adds a per-call aliasing handshake that slows the step ~5x.
    return jax.jit(train_step), params, opt_state


def timeit(step, params, opt_state, tokens, targets, iters):
    params, opt_state, loss = step(params, opt_state, tokens, targets)  # compile+warm
    float(loss)  # host fetch: the only reliable device sync over the tunnel
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    float(loss)  # forces completion of the whole dependent chain
    return (time.perf_counter() - t0) / iters


def main():
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = dict(vocab_size=16384, max_seq_len=1024, hidden_size=768,
                   num_layers=6, num_heads=12, tp_size=1, remat=False)
        batch, seq, iters = 8, 1024, 20
    else:  # smoke-test scale for CPU runs
        cfg = dict(vocab_size=1024, max_seq_len=128, hidden_size=128,
                   num_layers=2, num_heads=4, tp_size=1, remat=False)
        batch, seq, iters = 2, 128, 3

    tokens = jr.randint(jr.PRNGKey(1), (batch, seq), 0, cfg["vocab_size"])
    targets = jr.randint(jr.PRNGKey(2), (batch, seq), 0, cfg["vocab_size"])

    results = {}
    for impl in ("baseline", "fused"):
        os.environ["APEX_TPU_PALLAS"] = "0" if impl == "baseline" else "1"
        # drop cached modules so the env gate is re-read cleanly
        step, params, opt_state = build(impl, cfg)
        results[impl] = timeit(step, params, opt_state, tokens, targets, iters)
        del step, params, opt_state

    tokens_per_s = batch * seq / results["fused"]
    vs_baseline = results["baseline"] / results["fused"]
    print(json.dumps({
        "metric": "gpt_train_step_throughput",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
