"""Toy distributed MLP — port of ``examples/simple/distributed/``.

The reference's smallest end-to-end script: a tiny MLP under amp +
DistributedDataParallel, one process per GPU via ``torch.distributed.launch``.
Here the same run is a single SPMD program over the mesh's ``dp`` axis — run
it on any host (CPU mesh via XLA_FLAGS, or a TPU slice) with no launcher:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/simple/distributed/run.py --opt-level O2
"""

import argparse

import jax
import jax.numpy as jnp
import jax.random as jr
import optax
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel import mesh as mesh_lib


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O0", choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--loss-scale", default=None)
    args = p.parse_args()

    mesh = mesh_lib.initialize_model_parallel()  # dp = all devices
    dp = mesh_lib.get_data_parallel_world_size()
    print(f"devices: {jax.device_count()} (dp={dp}), opt_level={args.opt_level}")

    policy = amp.get_policy(args.opt_level)
    key = jr.PRNGKey(0)
    D, H = 64, 256
    params = {
        "w1": jr.normal(key, (D, H)) * 0.05, "b1": jnp.zeros((H,)),
        "w2": jr.normal(jr.fold_in(key, 1), (H, D)) * 0.05, "b2": jnp.zeros((D,)),
    }
    master = amp.MasterWeights.create(params, policy)
    # skip wrapper: an overflowed fp16 step must leave Adam's m/v
    # untouched, not just the params (cf. apex handle.py:128-154)
    opt = amp.skip_step_if_nonfinite(fused_adam(learning_rate=args.lr))
    opt_state = opt.init(master.master)
    scaler = amp.init_loss_scaler(args.loss_scale or "dynamic")

    W_true = jr.normal(jr.fold_in(key, 2), (D, D))

    def loss_fn(model_params, x, y):
        h = jnp.maximum(x @ model_params["w1"] + model_params["b1"], 0)
        out = h @ model_params["w2"] + model_params["b2"]
        return jnp.mean((out - y) ** 2)

    def train_step(master, opt_state, scaler, x, y):
        def run(master, opt_state, scaler, x, y):
            loss, (grads, finite, scaler) = amp.scaled_value_and_grad(loss_fn)(
                scaler, master.model, x, y)
            grads = jax.lax.pmean(grads, "dp")
            loss = jax.lax.pmean(loss, "dp")
            updates, opt_state = opt.update(grads, opt_state, master.master)
            master = amp.apply_updates_with_master(
                master, updates, grads_finite=finite)
            return master, opt_state, scaler, loss

        return mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(P(), P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P(), P()),
        )(master, opt_state, scaler, x, y)

    step = jax.jit(train_step)
    for i in range(args.steps):
        x = jr.normal(jr.fold_in(key, 100 + i), (8 * dp, D))
        y = jnp.tanh(x @ W_true)
        master, opt_state, scaler, loss = step(master, opt_state, scaler, x, y)
        if i % 20 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.5f}  "
                  f"scale {float(scaler.loss_scale):.0f}")


if __name__ == "__main__":
    main()
