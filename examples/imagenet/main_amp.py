"""ImageNet ResNet-50 — port of ``examples/imagenet/main_amp.py``.

The reference's flagship example (and the L1 convergence config,
``tests/L1/common/run_test.sh``): torchvision ResNet-50 under
``--opt-level O0..O3``, ``--loss-scale``, apex DDP, optional SyncBN. Here the
same flag surface drives the TPU-native stack: the dp mesh replaces DDP, the
precision policy replaces amp.initialize, SyncBatchNorm reduces over ``dp``.

Data: an ImageFolder-style directory of per-class .npy batches, or
``--synthetic`` for generated data (benchmark mode — the reference's
common usage with DALI disabled).

    python examples/imagenet/main_amp.py --synthetic --opt-level O2 \
        --sync-bn --batch-size 256 --iters 100
"""

import argparse
import time

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import ResNet50, ResNetConfig
from apex_tpu.optimizers import fused_sgd
from apex_tpu.ops import softmax_cross_entropy_loss
from apex_tpu.parallel import mesh as mesh_lib


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O2", choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--keep-batchnorm-fp32", default=None,
                   choices=[None, "True", "False"])
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--batch-size", type=int, default=256, help="global batch")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--deterministic", action="store_true",
                   help="fixed seeds + fresh deterministic batch per iter; "
                        "records the per-iteration loss curve (the "
                        "reference L1 tier's --deterministic contract)")
    p.add_argument("--label-smoothing", type=float, default=0.0)
    return p.parse_args(argv)


def train(args):
    """Run the example; returns the L1 record dict (per-iteration losses,
    skipped steps, throughput) — importable by the test tier the way the
    reference's run_test.sh shells out to main_amp.py --deterministic."""
    mesh = mesh_lib.initialize_model_parallel()
    dp = mesh_lib.get_data_parallel_world_size()
    kn = (None if args.keep_batchnorm_fp32 is None
          else args.keep_batchnorm_fp32 == "True")
    policy = amp.get_policy(args.opt_level, keep_norm_f32=kn)
    print(f"devices={jax.device_count()} dp={dp} opt_level={args.opt_level} "
          f"sync_bn={args.sync_bn} global_batch={args.batch_size}")

    model = ResNet50(ResNetConfig(
        num_classes=args.num_classes,
        bn_axis="dp" if args.sync_bn else None,
    ))
    params, bn_state = model.init(jr.PRNGKey(0))
    master = amp.MasterWeights.create(params, policy)
    opt = fused_sgd(learning_rate=args.lr, momentum=args.momentum,
                    weight_decay=args.weight_decay)
    opt_state = opt.init(master.master)
    scaler = amp.init_loss_scaler(args.loss_scale or "dynamic")

    def loss_fn(model_params, bn_state, x, y):
        logits, new_bn = model.apply(model_params, bn_state, x, training=True)
        losses = softmax_cross_entropy_loss(
            logits, y, args.label_smoothing, half_to_float=True)
        return jnp.mean(losses), new_bn

    def train_step(master, bn_state, opt_state, scaler, x, y):
        def run(master, bn_state, opt_state, scaler, x, y):
            # inputs follow the MODEL params' dtype (O0/O1 fp32 — O1's
            # per-op tables cast at wrapped-op entry; O2/O3 half). Casting
            # to compute_dtype under O1 would feed bf16 activations into
            # fp32 raw convs — exactly the mismatch the L1 tier caught.
            x = x.astype(policy.param_dtype)
            (loss, new_bn), (grads, finite, scaler) = amp.scaled_value_and_grad(
                loss_fn, has_aux=True)(scaler, master.model, bn_state, x, y)
            grads = jax.lax.pmean(grads, "dp")
            loss = jax.lax.pmean(loss, "dp")
            updates, opt_state = opt.update(grads, opt_state, master.master)
            master = amp.apply_updates_with_master(
                master, updates, grads_finite=finite)
            return master, new_bn, opt_state, scaler, loss

        return mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P(), P(), P()),
        )(master, bn_state, opt_state, scaler, x, y)

    step = jax.jit(train_step)
    key = jr.PRNGKey(1)
    b, s = args.batch_size, args.image_size
    x = jr.normal(key, (b, s, s, 3), jnp.float32)
    y = jr.randint(jr.fold_in(key, 1), (b,), 0, args.num_classes)

    if args.deterministic:
        # L1 mode: a FRESH deterministic batch each iteration (a real loss
        # curve, not one batch memorized), losses recorded per iteration.
        # Each class stamps a strong color-bias template on its images so
        # the task is learnable in tens of iterations.
        templates = jr.normal(jr.fold_in(key, 2),
                              (args.num_classes, 1, 1, 3)) * 2.0
        rec = {"iteration": [], "loss": []}
        t0 = time.perf_counter()
        for i in range(args.iters):
            k = jr.fold_in(key, 100 + i)
            y = jr.randint(k, (b,), 0, args.num_classes)
            x = (jr.normal(jr.fold_in(k, 1), (b, s, s, 3), jnp.float32)
                 + templates[y])
            master, bn_state, opt_state, scaler, loss = step(
                master, bn_state, opt_state, scaler, x, y)
            rec["iteration"].append(i)
            rec["loss"].append(float(loss))
        dt = time.perf_counter() - t0
        rec["skipped_steps"] = int(scaler.skipped_steps)
        rec["img_per_s"] = args.iters * b / dt
        return rec

    if args.synthetic:
        # warm TWICE: the first call compiles against the freshly-created
        # state's shardings; feeding outputs back changes the input avals
        # (shard_map outputs carry explicit NamedShardings) and triggers one
        # more compile — both must happen outside the timed region
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, x, y)
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, x, y)
        float(loss)
        t0 = time.perf_counter()
        for i in range(args.iters):
            master, bn_state, opt_state, scaler, loss = step(
                master, bn_state, opt_state, scaler, x, y)
    else:
        # host batches through the double-buffered prefetcher: batch i+1's
        # dp-sharded device_put overlaps step i (the DataLoader
        # pinned-memory overlap, TPU-style)
        from apex_tpu.transformer._data import data_parallel_iterator

        def host_batches():
            rng = np.random.default_rng(0)
            for _ in range(args.iters + 1):
                yield (rng.standard_normal((b, s, s, 3), dtype=np.float32),
                       rng.integers(0, args.num_classes, (b,)))

        it = data_parallel_iterator(host_batches())
        # warm with a SHARDED batch — the sharding is part of the jit cache
        # key, so warming unsharded would recompile inside the timed loop —
        # and twice, so the fed-back state's NamedShardings compile too
        xb, yb = next(it)
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, xb, yb)
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, xb, yb)
        float(loss)
        t0 = time.perf_counter()
        for xb, yb in it:
            master, bn_state, opt_state, scaler, loss = step(
                master, bn_state, opt_state, scaler, xb, yb)
    lv = float(loss)  # hard sync
    dt = time.perf_counter() - t0
    return {"loss": [lv], "img_per_s": args.iters * b / dt,
            "ms_per_step": dt / args.iters * 1e3,
            "skipped_steps": int(scaler.skipped_steps)}


def main():
    args = parse_args()
    rec = train(args)
    if args.deterministic:
        print(f"final loss {rec['loss'][-1]:.4f}  "
              f"skipped {rec['skipped_steps']}  "
              f"{rec['img_per_s']:.1f} img/s")
    else:
        print(f"loss {rec['loss'][-1]:.4f}  throughput "
              f"{rec['img_per_s']:.1f} img/s "
              f"({rec['ms_per_step']:.1f} ms/step)")


if __name__ == "__main__":
    main()
