"""ImageNet ResNet-50 — port of ``examples/imagenet/main_amp.py``.

The reference's flagship example (and the L1 convergence config,
``tests/L1/common/run_test.sh``): torchvision ResNet-50 under
``--opt-level O0..O3``, ``--loss-scale``, apex DDP, optional SyncBN. Here the
same flag surface drives the TPU-native stack: the dp mesh replaces DDP, the
precision policy replaces amp.initialize, SyncBatchNorm reduces over ``dp``.

Data: an ImageFolder-style directory of per-class .npy batches, or
``--synthetic`` for generated data (benchmark mode — the reference's
common usage with DALI disabled).

    python examples/imagenet/main_amp.py --synthetic --opt-level O2 \
        --sync-bn --batch-size 256 --iters 100
"""

import argparse
import time

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import ResNet50, ResNetConfig
from apex_tpu.optimizers import fused_sgd
from apex_tpu.ops import softmax_cross_entropy_loss
from apex_tpu.parallel import mesh as mesh_lib


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O2", choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--batch-size", type=int, default=256, help="global batch")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--label-smoothing", type=float, default=0.0)
    return p.parse_args()


def main():
    args = parse_args()
    mesh = mesh_lib.initialize_model_parallel()
    dp = mesh_lib.get_data_parallel_world_size()
    policy = amp.get_policy(args.opt_level)
    print(f"devices={jax.device_count()} dp={dp} opt_level={args.opt_level} "
          f"sync_bn={args.sync_bn} global_batch={args.batch_size}")

    model = ResNet50(ResNetConfig(
        num_classes=args.num_classes,
        bn_axis="dp" if args.sync_bn else None,
    ))
    params, bn_state = model.init(jr.PRNGKey(0))
    master = amp.MasterWeights.create(params, policy)
    opt = fused_sgd(learning_rate=args.lr, momentum=args.momentum,
                    weight_decay=args.weight_decay)
    opt_state = opt.init(master.master)
    scaler = amp.init_loss_scaler(args.loss_scale or "dynamic")

    def loss_fn(model_params, bn_state, x, y):
        logits, new_bn = model.apply(model_params, bn_state, x, training=True)
        losses = softmax_cross_entropy_loss(
            logits, y, args.label_smoothing, half_to_float=True)
        return jnp.mean(losses), new_bn

    def train_step(master, bn_state, opt_state, scaler, x, y):
        def run(master, bn_state, opt_state, scaler, x, y):
            x = x.astype(policy.compute_dtype)
            (loss, new_bn), (grads, finite, scaler) = amp.scaled_value_and_grad(
                loss_fn, has_aux=True)(scaler, master.model, bn_state, x, y)
            grads = jax.lax.pmean(grads, "dp")
            loss = jax.lax.pmean(loss, "dp")
            updates, opt_state = opt.update(grads, opt_state, master.master)
            master = amp.apply_updates_with_master(
                master, updates, grads_finite=finite)
            return master, new_bn, opt_state, scaler, loss

        return mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P(), P(), P()),
        )(master, bn_state, opt_state, scaler, x, y)

    step = jax.jit(train_step)
    key = jr.PRNGKey(1)
    b, s = args.batch_size, args.image_size
    x = jr.normal(key, (b, s, s, 3), jnp.float32)
    y = jr.randint(jr.fold_in(key, 1), (b,), 0, args.num_classes)

    if args.synthetic:
        # warm TWICE: the first call compiles against the freshly-created
        # state's shardings; feeding outputs back changes the input avals
        # (shard_map outputs carry explicit NamedShardings) and triggers one
        # more compile — both must happen outside the timed region
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, x, y)
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, x, y)
        float(loss)
        t0 = time.perf_counter()
        for i in range(args.iters):
            master, bn_state, opt_state, scaler, loss = step(
                master, bn_state, opt_state, scaler, x, y)
    else:
        # host batches through the double-buffered prefetcher: batch i+1's
        # dp-sharded device_put overlaps step i (the DataLoader
        # pinned-memory overlap, TPU-style)
        from apex_tpu.transformer._data import data_parallel_iterator

        def host_batches():
            rng = np.random.default_rng(0)
            for _ in range(args.iters + 1):
                yield (rng.standard_normal((b, s, s, 3), dtype=np.float32),
                       rng.integers(0, args.num_classes, (b,)))

        it = data_parallel_iterator(host_batches())
        # warm with a SHARDED batch — the sharding is part of the jit cache
        # key, so warming unsharded would recompile inside the timed loop —
        # and twice, so the fed-back state's NamedShardings compile too
        xb, yb = next(it)
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, xb, yb)
        master, bn_state, opt_state, scaler, loss = step(
            master, bn_state, opt_state, scaler, xb, yb)
        float(loss)
        t0 = time.perf_counter()
        for xb, yb in it:
            master, bn_state, opt_state, scaler, loss = step(
                master, bn_state, opt_state, scaler, xb, yb)
    lv = float(loss)  # hard sync
    dt = time.perf_counter() - t0
    print(f"loss {lv:.4f}  throughput {args.iters * b / dt:.1f} img/s "
          f"({dt / args.iters * 1e3:.1f} ms/step)")


if __name__ == "__main__":
    main()
