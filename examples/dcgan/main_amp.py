"""DCGAN under mixed precision — port of ``examples/dcgan/main_amp.py``.

The reference demonstrates amp's multiple-models / multiple-optimizers /
multiple-losses surface (``amp.initialize([netD, netG], [optD, optG],
num_losses=3)`` and three ``scale_loss(..., loss_id=i)`` contexts). The
functional translation: one policy, three independent loss-scaler states
(errD_real, errD_fake, errG), two optimizers — no patching.

The discriminator loss is binary cross-entropy on probabilities — the
canonical *banned* fp16 op (``lists/functional_overrides.py:69-80``): under
O1 the loss runs in fp32 (policy casts network outputs up), exactly the
reference's behavior.

Run (CPU smoke): JAX_PLATFORMS=cpu python examples/dcgan/main_amp.py \
    --niter 2 --iters-per-epoch 4 --imageSize 16
"""

import argparse

import jax
import jax.numpy as jnp
import jax.random as jr
import optax

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam
from apex_tpu.ops.xentropy import binary_cross_entropy


def conv(x, w, stride=2):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def conv_t(x, w, stride=2):
    return jax.lax.conv_transpose(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def init_generator(key, nz, ngf, image_size):
    s0 = image_size // 4
    ks = jr.split(key, 3)
    return {
        "fc": jr.normal(ks[0], (nz, s0 * s0 * ngf * 2)) * 0.05,
        "ct1": jr.normal(ks[1], (4, 4, ngf * 2, ngf)) * 0.05,
        "ct2": jr.normal(ks[2], (4, 4, ngf, 3)) * 0.05,
    }


def generator(p, z):
    z = z.astype(p["fc"].dtype)  # follow the policy's compute dtype
    # spatial start size / width are static shapes recovered from the params
    ngf = p["ct1"].shape[3]
    s0 = int((p["fc"].shape[1] // (ngf * 2)) ** 0.5)
    h = jax.nn.relu(z @ p["fc"]).reshape(z.shape[0], s0, s0, ngf * 2)
    h = jax.nn.relu(conv_t(h, p["ct1"]))
    return jnp.tanh(conv_t(h, p["ct2"]))


def init_discriminator(key, ndf):
    ks = jr.split(key, 3)
    return {
        "c1": jr.normal(ks[0], (4, 4, 3, ndf)) * 0.05,
        "c2": jr.normal(ks[1], (4, 4, ndf, ndf * 2)) * 0.05,
        "fc": jr.normal(ks[2], (ndf * 2, 1)) * 0.05,
    }


def discriminator(p, x):
    x = x.astype(p["c1"].dtype)  # follow the policy's compute dtype
    h = jax.nn.leaky_relu(conv(x, p["c1"]), 0.2)
    h = jax.nn.leaky_relu(conv(h, p["c2"]), 0.2)
    h = h.mean(axis=(1, 2))
    return jax.nn.sigmoid(h @ p["fc"])[:, 0]


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset", default="fake", help="fake: synthetic data")
    parser.add_argument("--batchSize", type=int, default=64)
    parser.add_argument("--imageSize", type=int, default=16)
    parser.add_argument("--nz", type=int, default=100)
    parser.add_argument("--ngf", type=int, default=64)
    parser.add_argument("--ndf", type=int, default=64)
    parser.add_argument("--niter", type=int, default=25)
    parser.add_argument("--iters-per-epoch", type=int, default=8)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--beta1", type=float, default=0.5)
    parser.add_argument("--manualSeed", type=int, default=2809)
    parser.add_argument("--opt_level", default="O1")
    return parser.parse_args(argv)


def train(args, verbose: bool = True):
    """Run the example; returns the L1 record (per-step D/G loss curves +
    final dynamic scales) for the test tier."""
    policy = amp.get_policy(args.opt_level)
    key = jr.PRNGKey(args.manualSeed)
    netG = init_generator(jr.fold_in(key, 0), args.nz, args.ngf, args.imageSize)
    netD = init_discriminator(jr.fold_in(key, 1), args.ndf)
    mG = amp.MasterWeights.create(netG, policy)
    mD = amp.MasterWeights.create(netD, policy)

    optG = amp.skip_step_if_nonfinite(
        fused_adam(learning_rate=args.lr, b1=args.beta1, b2=0.999))
    optD = amp.skip_step_if_nonfinite(
        fused_adam(learning_rate=args.lr, b1=args.beta1, b2=0.999))
    stG, stD = optG.init(mG.master), optD.init(mD.master)
    # three scalers, one per loss — the reference's num_losses=3 /
    # loss_id protocol (main_amp.py: scale_loss(errD_real, optimizerD, 0)...)
    scalers = [amp.init_loss_scaler("dynamic") for _ in range(3)]

    real_label, fake_label = 1.0, 0.0

    def d_loss_real(dp, x):
        out = discriminator(policy.cast_to_compute(dp), x).astype(jnp.float32)
        return binary_cross_entropy(out, jnp.full_like(out, real_label)).mean()

    def d_loss_fake(dp, fake):
        out = discriminator(policy.cast_to_compute(dp), fake).astype(jnp.float32)
        return binary_cross_entropy(out, jnp.full_like(out, fake_label)).mean()

    def g_loss(gp, dp, z):
        fake = generator(policy.cast_to_compute(gp), z)
        out = discriminator(policy.cast_to_compute(dp), fake).astype(jnp.float32)
        return binary_cross_entropy(out, jnp.full_like(out, real_label)).mean()

    @jax.jit
    def train_step(mG, mD, stG, stD, s0, s1, s2, x, z):
        with amp.with_policy(policy):
            fake = generator(policy.cast_to_compute(mG.model), z)
            # D step: two scaled losses, summed grads (reference backward()s
            # errD_real and errD_fake separately into the same grads)
            lr_, (gr, fr, s0) = amp.scaled_value_and_grad(d_loss_real)(
                s0, mD.model, policy.cast_to_compute(x))
            lf_, (gf, ff, s1) = amp.scaled_value_and_grad(d_loss_fake)(
                s1, mD.model, jax.lax.stop_gradient(fake))
            gD = jax.tree.map(jnp.add, gr, gf)
            finD = jnp.logical_and(fr, ff)
            upD, stD = optD.update(gD, stD, mD.master)
            mD = amp.apply_updates_with_master(mD, upD, grads_finite=finD)

            # G step through the updated D
            lg_, (gG, fg, s2) = amp.scaled_value_and_grad(
                lambda gp, z: g_loss(gp, mD.model, z))(s2, mG.model, z)
            upG, stG = optG.update(gG, stG, mG.master)
            mG = amp.apply_updates_with_master(mG, upG, grads_finite=fg)
        return mG, mD, stG, stD, s0, s1, s2, lr_ + lf_, lg_

    rec = {"iteration": [], "loss_d": [], "loss_g": []}
    it = 0
    for epoch in range(args.niter):
        for i in range(args.iters_per_epoch):
            k = jr.fold_in(key, epoch * 10000 + i)
            # dataset='fake': smooth random blobs as the real distribution
            base = jr.normal(jr.fold_in(k, 0),
                             (args.batchSize, 4, 4, 3))
            x = jax.image.resize(
                base, (args.batchSize, args.imageSize, args.imageSize, 3),
                "linear").clip(-1, 1)
            z = jr.normal(jr.fold_in(k, 1), (args.batchSize, args.nz))
            (mG, mD, stG, stD, scalers[0], scalers[1], scalers[2],
             lossD, lossG) = train_step(
                mG, mD, stG, stD, *scalers, x, z)
            rec["iteration"].append(it)
            rec["loss_d"].append(float(lossD))
            rec["loss_g"].append(float(lossG))
            it += 1
        if verbose:
            print(f"[{epoch}/{args.niter}] Loss_D: {float(lossD):.4f} "
                  f"Loss_G: {float(lossG):.4f} "
                  f"scale: {float(scalers[0].loss_scale):.0f}")

    assert jnp.isfinite(lossD) and jnp.isfinite(lossG)
    rec["skipped_steps"] = sum(int(s.skipped_steps) for s in scalers)
    rec["final_scales"] = [float(s.loss_scale) for s in scalers]
    return rec


def main():
    args = parse_args()
    print(args)
    train(args)
    print("done")


if __name__ == "__main__":
    main()
