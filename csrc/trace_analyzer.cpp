// Trace aggregator — native post-processor for profiler op records.
//
// TPU-native equivalent of apex.pyprof's analysis stage
// (apex/pyprof/prof/*.py: per-kernel FLOPs/bytes aggregation over nvprof
// SQLite dumps — the reference does this in Python over potentially millions
// of kernel records). Here the op records arrive as a compact JSON array
// [{"f": family, "flops": F, "bytes": B, "t": T}, ...] and are reduced to
// per-family (count, flops, bytes, time) in one pass.
//
// Exposed C ABI (ctypes):
//   aggregate_trace_json(json, out_buf, out_cap) -> written bytes (or -1)
//   Output: JSON {"family": {"count": n, "flops": f, "bytes": b, "t": t}, ...}

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>

namespace {

struct Agg {
  int64_t count = 0;
  double flops = 0, bytes = 0, t = 0;
};

// minimal JSON scanning for the fixed record schema (no general parser —
// the producer is our own analyzer.py)
const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\n' || *p == '\t' || *p == ',') ++p;
  return p;
}

bool parse_string(const char*& p, std::string* out) {
  if (*p != '"') return false;
  ++p;
  out->clear();
  while (*p && *p != '"') out->push_back(*p++);
  if (*p != '"') return false;
  ++p;
  return true;
}

bool parse_number(const char*& p, double* out) {
  char* end = nullptr;
  *out = strtod(p, &end);
  if (end == p) return false;
  p = end;
  return true;
}

}  // namespace

extern "C" {

int64_t aggregate_trace_json(const char* json, char* out_buf, int64_t out_cap) {
  std::map<std::string, Agg> agg;
  const char* p = skip_ws(json);
  if (*p != '[') return -1;
  ++p;
  while (true) {
    p = skip_ws(p);
    if (*p == ']' || *p == '\0') break;
    if (*p != '{') return -1;
    ++p;
    std::string fam;
    double flops = 0, bytes = 0, t = 0;
    while (true) {
      p = skip_ws(p);
      if (*p == '}') { ++p; break; }
      std::string key;
      if (!parse_string(p, &key)) return -1;
      p = skip_ws(p);
      if (*p != ':') return -1;
      ++p;
      p = skip_ws(p);
      if (key == "f") {
        if (!parse_string(p, &fam)) return -1;
      } else {
        double v;
        if (!parse_number(p, &v)) return -1;
        if (key == "flops") flops = v;
        else if (key == "bytes") bytes = v;
        else if (key == "t") t = v;
      }
    }
    Agg& a = agg[fam];
    a.count += 1;
    a.flops += flops;
    a.bytes += bytes;
    a.t += t;
  }

  std::string out = "{";
  bool first = true;
  char buf[256];
  for (const auto& kv : agg) {
    if (!first) out += ",";
    first = false;
    snprintf(buf, sizeof(buf),
             "\"%s\":{\"count\":%lld,\"flops\":%.17g,\"bytes\":%.17g,\"t\":%.17g}",
             kv.first.c_str(), (long long)kv.second.count, kv.second.flops,
             kv.second.bytes, kv.second.t);
    out += buf;
  }
  out += "}";
  if ((int64_t)out.size() + 1 > out_cap) return -1;
  memcpy(out_buf, out.c_str(), out.size() + 1);
  return (int64_t)out.size();
}

}  // extern "C"
