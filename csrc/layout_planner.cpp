// Chunk-layout planner — native host-side metadata construction.
//
// TPU-native equivalent of the host side of the reference's multi-tensor
// machinery: apex_C's flatten bookkeeping (csrc/flatten_unflatten.cpp) and
// the chunk-metadata packing loop of multi_tensor_apply
// (csrc/multi_tensor_apply.cuh:41-133), which walks every tensor computing
// per-chunk (tensor index, chunk offset) records before each launch. Here
// the same walk produces the chunk->tensor map and per-tensor offsets that
// apex_tpu.optimizers.multi_tensor uses to drive its fused XLA updates —
// O(total_chunks) C with no Python-loop overhead for models with very many
// parameter tensors.
//
// Exposed C ABI (ctypes):
//   plan_layout(sizes, n_tensors, chunk_size, chunk_to_tensor_out,
//               tensor_offset_out) -> total_chunks
//   (chunk_to_tensor_out sized by a prior call with outputs null.)

#include <cstdint>
#include <cstddef>

extern "C" {

// Returns the number of chunks the layout needs; fills outputs when non-null.
// sizes[i]: element count of tensor i. Every tensor owns >= 1 chunk
// (zero-sized tensors still get a placeholder chunk, matching
// multi_tensor.make_layout's max(1, ceil(size/chunk))).
int64_t plan_layout(const int64_t* sizes, int64_t n_tensors, int64_t chunk_size,
                    int32_t* chunk_to_tensor_out, int64_t* tensor_offset_out) {
  int64_t total = 0;
  for (int64_t i = 0; i < n_tensors; ++i) {
    int64_t chunks = (sizes[i] + chunk_size - 1) / chunk_size;
    if (chunks == 0) chunks = 1;
    if (tensor_offset_out) tensor_offset_out[i] = total * chunk_size;
    if (chunk_to_tensor_out) {
      for (int64_t c = 0; c < chunks; ++c) chunk_to_tensor_out[total + c] = (int32_t)i;
      total += chunks;
    } else {
      total += chunks;
    }
  }
  return total;
}

}  // extern "C"
