// Gzipped chrome-trace parser — the native IO stage of the profiler
// pipeline.
//
// The reference's parse stage reads the nvprof SQLite database in C
// (sqlite3 via apex/pyprof/parse/db.py); the TPU trace artifact is the
// multi-megabyte trace.json.gz that jax.profiler writes. Loading that
// through Python's json module dominates post-processing time for real
// traces, so this file does the whole IO stage natively: gunzip (zlib),
// scan the JSON event stream, resolve process/thread metadata, and emit
// only the compact per-event records apex_tpu.prof.trace_reader needs
// (name/ts/dur/device/track + the XProf cost-model args).
//
// Exposed C ABI (ctypes):
//   parse_trace_gz(path, &out) -> bytes written (malloc'd; -1 on error)
//   free_buffer(out)
//
// Output JSON: [{"name":..,"ts":..,"dur":..,"device":..,"track":..,
//                "args":{subset}}, ...]

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------- gunzip

bool read_gz(const char* path, std::string* out) {
  gzFile f = gzopen(path, "rb");
  if (!f) return false;
  char buf[1 << 16];
  int n;
  while ((n = gzread(f, buf, sizeof(buf))) > 0) out->append(buf, n);
  bool ok = (n == 0);
  gzclose(f);
  return ok;
}

// ------------------------------------------------- minimal JSON parser
// Full-fidelity scanning parser for the subset of JSON that chrome traces
// use; values we don't need are skipped without materialization.

struct Parser {
  const char* p;
  const char* end;

  explicit Parser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == c) { ++p; return true; }
    return false;
  }
  char peek() {
    ws();
    return p < end ? *p : '\0';
  }

  // parse a JSON string into out (unescaped)
  bool string(std::string* out) {
    ws();
    if (p >= end || *p != '"') return false;
    ++p;
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out->push_back('\n'); break;
          case 't': out->push_back('\t'); break;
          case 'r': out->push_back('\r'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'u': {
            // keep BMP escapes as '?' placeholders — names we care about
            // are ASCII; fidelity here doesn't affect aggregation
            if (end - p >= 5) p += 4;
            out->push_back('?');
            break;
          }
          default: out->push_back(*p); break;
        }
        ++p;
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) return false;
    ++p;  // closing quote
    return true;
  }

  bool number(double* out) {
    ws();
    char* e = nullptr;
    *out = strtod(p, &e);
    if (e == p) return false;
    p = e;
    return true;
  }

  // skip any JSON value
  bool skip() {
    ws();
    if (p >= end) return false;
    switch (*p) {
      case '"': { std::string s; return string(&s); }
      case '{': {
        ++p;
        if (eat('}')) return true;
        while (true) {
          std::string k;
          if (!string(&k) || !eat(':') || !skip()) return false;
          if (eat('}')) return true;
          if (!eat(',')) return false;
        }
      }
      case '[': {
        ++p;
        if (eat(']')) return true;
        while (true) {
          if (!skip()) return false;
          if (eat(']')) return true;
          if (!eat(',')) return false;
        }
      }
      case 't': p += 4; return p <= end;
      case 'f': p += 5; return p <= end;
      case 'n': p += 4; return p <= end;
      default: { double d; return number(&d); }
    }
  }
};

// ------------------------------------------------------------- emitter

void json_escape(const std::string& s, std::string* out) {
  for (char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\t': *out += "\\t"; break;
      case '\r': *out += "\\r"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(c);
        }
    }
  }
}

// the XProf args the analyzer consumes (analyzer.py / trace_reader.py)
bool wanted_arg(const std::string& k) {
  return k == "model_flops" || k == "bytes_accessed" ||
         k == "raw_bytes_accessed" || k == "hlo_category" || k == "source" ||
         k == "flops" || k == "bytes" || k == "bytes accessed";
}

struct Event {
  std::string name;
  double ts = 0, dur = 0;
  int64_t pid = -1, tid = -1;
  std::string args_json;  // pre-serialized subset
};

}  // namespace

extern "C" {

void free_buffer(char* buf) { free(buf); }

int64_t parse_trace_gz(const char* path, char** out_buf) {
  std::string raw;
  if (!read_gz(path, &raw)) return -1;

  Parser ps(raw);
  std::vector<Event> events;
  std::map<int64_t, std::string> procs;
  std::map<std::pair<int64_t, int64_t>, std::string> threads;

  // top level: {"traceEvents": [...], ...}
  if (!ps.eat('{')) return -1;
  bool found = false;
  while (!found) {
    std::string key;
    if (!ps.string(&key) || !ps.eat(':')) return -1;
    if (key == "traceEvents") {
      found = true;
      break;
    }
    if (!ps.skip()) return -1;
    if (!ps.eat(',')) return -1;  // traceEvents must still be ahead
  }
  if (!ps.eat('[')) return -1;

  if (ps.peek() != ']') {
    do {
      if (!ps.eat('{')) return -1;
      Event ev;
      std::string ph, meta_name, meta_arg_name;
      bool have_args = false;
      if (ps.peek() != '}') {
        do {
          std::string key;
          if (!ps.string(&key) || !ps.eat(':')) return -1;
          if (key == "ph") {
            if (!ps.string(&ph)) return -1;
          } else if (key == "name") {
            if (!ps.string(&ev.name)) return -1;
          } else if (key == "ts") {
            if (!ps.number(&ev.ts)) return -1;
          } else if (key == "dur") {
            if (!ps.number(&ev.dur)) return -1;
          } else if (key == "pid" || key == "tid") {
            double d;
            if (!ps.number(&d)) return -1;
            (key == "pid" ? ev.pid : ev.tid) = (int64_t)d;
          } else if (key == "args") {
            // inline-parse the args object, keeping wanted keys
            have_args = true;
            if (!ps.eat('{')) { if (!ps.skip()) return -1; }
            else if (ps.peek() == '}') { ps.eat('}'); }
            else {
              std::string acc;
              do {
                std::string ak;
                if (!ps.string(&ak) || !ps.eat(':')) return -1;
                if (ak == "name") {  // metadata payload
                  if (ps.peek() == '"') {
                    if (!ps.string(&meta_arg_name)) return -1;
                  } else if (!ps.skip()) return -1;
                } else if (wanted_arg(ak)) {
                  std::string sval;
                  double dval;
                  if (ps.peek() == '"') {
                    if (!ps.string(&sval)) return -1;
                    if (!acc.empty()) acc += ",";
                    acc += "\"";
                    json_escape(ak, &acc);
                    acc += "\":\"";
                    json_escape(sval, &acc);
                    acc += "\"";
                  } else if (ps.peek() == '{' || ps.peek() == '[' ||
                             ps.peek() == 't' || ps.peek() == 'f' ||
                             ps.peek() == 'n') {
                    if (!ps.skip()) return -1;
                  } else {
                    if (!ps.number(&dval)) return -1;
                    char buf[40];
                    snprintf(buf, sizeof(buf), "%.17g", dval);
                    if (!acc.empty()) acc += ",";
                    acc += "\"";
                    json_escape(ak, &acc);
                    acc += "\":";
                    acc += buf;
                  }
                } else {
                  if (!ps.skip()) return -1;
                }
              } while (ps.eat(','));
              if (!ps.eat('}')) return -1;
              ev.args_json = "{" + acc + "}";
            }
          } else {
            if (!ps.skip()) return -1;
          }
        } while (ps.eat(','));
      }
      if (!ps.eat('}')) return -1;

      if (ph == "M") {
        if (ev.name == "process_name" && ev.pid >= 0)
          procs[ev.pid] = meta_arg_name;
        else if (ev.name == "thread_name" && ev.pid >= 0)
          threads[{ev.pid, ev.tid}] = meta_arg_name;
      } else if (ph == "X") {
        if (!have_args || ev.args_json.empty()) ev.args_json = "{}";
        events.push_back(std::move(ev));
      }
    } while (ps.eat(','));
  }
  if (!ps.eat(']')) return -1;

  // resolve + emit
  std::string out = "[";
  char buf[64];
  for (size_t i = 0; i < events.size(); ++i) {
    const Event& ev = events[i];
    if (i) out += ",";
    out += "{\"name\":\"";
    json_escape(ev.name, &out);
    out += "\",\"ts\":";
    snprintf(buf, sizeof(buf), "%.17g", ev.ts);
    out += buf;
    out += ",\"dur\":";
    snprintf(buf, sizeof(buf), "%.17g", ev.dur);
    out += buf;
    out += ",\"device\":\"";
    auto pit = procs.find(ev.pid);
    if (pit != procs.end()) json_escape(pit->second, &out);
    out += "\",\"track\":\"";
    auto tit = threads.find({ev.pid, ev.tid});
    if (tit != threads.end()) json_escape(tit->second, &out);
    out += "\",\"args\":";
    out += ev.args_json;
    out += "}";
  }
  out += "]";

  char* mem = (char*)malloc(out.size() + 1);
  if (!mem) return -1;
  memcpy(mem, out.c_str(), out.size() + 1);
  *out_buf = mem;
  return (int64_t)out.size();
}

}  // extern "C"
