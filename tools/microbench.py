"""Component microbenchmarks against plain-XLA baselines — the measurable
targets in BASELINE.md ("FusedAdam/FusedLAMB step time: beat unfused optax
on 1M-param MLP"; "FusedLayerNorm/RMSNorm + fused_dense block: beat
plain-XLA reference").

    python tools/microbench.py            # run on whatever backend is live

Prints one line per benchmark: name, framework time, baseline time, ratio.
Measured numbers are recorded in PERF.md.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.random as jr


def timeit(fn, *args, iters=50, repeats=5):
    """Min of ``repeats`` means over ``iters`` calls — sub-ms kernels through
    the remote tunnel need the min to strip transport noise."""
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def report(name, ours, base):
    print(f"{name:<38} ours {ours*1e3:8.3f} ms   baseline {base*1e3:8.3f} ms"
          f"   x{base/ours:.2f}")


def bench_fused_adam():
    """Chunked FusedAdam vs unfused optax.adam on a ~1M-param MLP pytree."""
    import optax

    from apex_tpu.optimizers import fused_adam

    key = jr.PRNGKey(0)
    # a realistic many-tensor pytree: 8 layers of (weight, bias)
    params = {}
    for i in range(8):
        k1, key = jr.split(key)
        params[f"w{i}"] = jr.normal(k1, (360, 360), jnp.float32)
        params[f"b{i}"] = jnp.zeros((360,), jnp.float32)
    grads = jax.tree.map(lambda x: jnp.ones_like(x) * 1e-3, params)

    ours_opt = fused_adam(learning_rate=1e-3)
    base_opt = optax.adam(1e-3)

    def step(opt):
        state = opt.init(params)

        @jax.jit
        def f(params, state, grads):
            updates, state = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        return timeit(f, params, state, grads)

    report("fused_adam vs optax.adam (1M params)", step(ours_opt), step(base_opt))


def bench_fused_lamb():
    import optax

    from apex_tpu.optimizers import fused_lamb

    key = jr.PRNGKey(1)
    params = {}
    for i in range(8):
        k1, key = jr.split(key)
        params[f"w{i}"] = jr.normal(k1, (360, 360), jnp.float32)
        params[f"b{i}"] = jnp.zeros((360,), jnp.float32)
    grads = jax.tree.map(lambda x: jnp.ones_like(x) * 1e-3, params)

    def step(opt):
        state = opt.init(params)

        @jax.jit
        def f(params, state, grads):
            updates, state = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        return timeit(f, params, state, grads)

    report("fused_lamb vs optax lamb (1M params)",
           step(fused_lamb(learning_rate=1e-3)),
           step(optax.lamb(1e-3)))


def bench_layer_norm():
    """Pallas LN fwd+bwd vs jnp composition, transformer-shaped input."""
    from apex_tpu.ops.layer_norm import fused_layer_norm

    x = jr.normal(jr.PRNGKey(2), (16 * 1024, 1024), jnp.bfloat16)
    g = jnp.ones((1024,), jnp.bfloat16)
    b = jnp.zeros((1024,), jnp.bfloat16)

    def ours_loss(x, g, b):
        return jnp.sum(fused_layer_norm(x, g, b).astype(jnp.float32))

    def base_loss(x, g, b):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + 1e-5) * g.astype(jnp.float32) + b.astype(jnp.float32)
        return jnp.sum(y)

    ours = jax.jit(jax.grad(ours_loss, argnums=(0, 1, 2)))
    base = jax.jit(jax.grad(base_loss, argnums=(0, 1, 2)))
    report("fused layer_norm fwd+bwd (16k x 1024)",
           timeit(ours, x, g, b), timeit(base, x, g, b))


def bench_fused_dense_gelu_dense():
    """DenseGeluDense block vs naive chained jnp ops."""
    from apex_tpu.ops.fused_dense import fused_dense_gelu_dense

    H, F = 1024, 4096
    x = jr.normal(jr.PRNGKey(3), (16 * 128, H), jnp.bfloat16)
    # torch (out_features, in_features) convention, matching the module
    w1 = jr.normal(jr.PRNGKey(4), (F, H), jnp.bfloat16) * 0.02
    b1 = jnp.zeros((F,), jnp.bfloat16)
    w2 = jr.normal(jr.PRNGKey(5), (H, F), jnp.bfloat16) * 0.02
    b2 = jnp.zeros((H,), jnp.bfloat16)

    def ours_loss(x, w1, b1, w2, b2):
        return jnp.sum(fused_dense_gelu_dense(x, w1, b1, w2, b2).astype(jnp.float32))

    def base_loss(x, w1, b1, w2, b2):
        h = jax.nn.gelu(x @ w1.T + b1)
        return jnp.sum((h @ w2.T + b2).astype(jnp.float32))

    ours = jax.jit(jax.grad(ours_loss, argnums=(0, 1, 2, 3, 4)))
    base = jax.jit(jax.grad(base_loss, argnums=(0, 1, 2, 3, 4)))
    report("dense_gelu_dense fwd+bwd (2k x 1024x4096)",
           timeit(ours, x, w1, b1, w2, b2), timeit(base, x, w1, b1, w2, b2))


def bench_softmax_xentropy():
    """Fused softmax-CE vs naive log_softmax + gather (32k vocab)."""
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    logits = jr.normal(jr.PRNGKey(6), (8 * 1024, 32768), jnp.float32)
    labels = jr.randint(jr.PRNGKey(7), (8 * 1024,), 0, 32768)

    def ours_loss(logits, labels):
        return jnp.mean(softmax_cross_entropy_loss(logits, labels))

    def base_loss(logits, labels):
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))

    ours = jax.jit(jax.grad(ours_loss))
    base = jax.jit(jax.grad(base_loss))
    report("softmax_xentropy fwd+bwd (8k x 32768)",
           timeit(ours, logits, labels), timeit(base, logits, labels))


def main():
    os.environ.setdefault("APEX_TPU_PALLAS", "1")
    print(f"backend: {jax.default_backend()} "
          f"({jax.devices()[0].device_kind})")
    bench_fused_adam()
    bench_fused_lamb()
    bench_layer_norm()
    bench_fused_dense_gelu_dense()
    bench_softmax_xentropy()


if __name__ == "__main__":
    main()
