"""Component microbenchmarks against plain-XLA baselines — the measurable
targets in BASELINE.md ("FusedAdam/FusedLAMB step time: beat unfused optax
on 1M-param MLP"; "FusedLayerNorm/RMSNorm + fused_dense block: beat
plain-XLA reference").

    python tools/microbench.py            # run on whatever backend is live

Prints one line per benchmark: name, framework time, baseline time, ratio.
Measured numbers are recorded in PERF.md.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.random as jr


def timeit(step, carry, iters=64, repeats=3):
    """Per-iteration device time of ``step: carry -> carry`` via an
    on-device ``fori_loop`` and slope timing.

    Host-side timing is useless for sub-ms kernels here: through the remote
    tunnel ``block_until_ready`` returns at *dispatch* (a 1-TFLOP matmul
    "measured" 0.03 ms), and forcing completion with a per-call host fetch
    buries the kernel under ~2.5 ms of per-call transport. And a loop whose
    iterations don't feed each other lets XLA hoist loop-invariant work and
    dead-code-eliminate everything but the one fetched element (optax.adam
    "measured" 0.000 ms that way). So: the benchmarked op must be a
    self-feeding carry update, ``fori_loop``-ed long enough (~1 s) that the
    single dispatch + scalar fetch is <1% of the span; the carry dependence
    forces every iteration to execute in full. (A (t(2N)-t(N))/N slope was
    tried first — differencing two separate dispatches through the tunnel
    amplified its multi-ms drift into nonsense for sub-ms ops.)
    """

    def run_time(n):
        @jax.jit
        def run(c):
            return jax.lax.fori_loop(0, n, lambda i, c: step(c), c)

        out = run(carry)
        float(jax.tree.leaves(out)[0].ravel()[0])  # fetch = real completion
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = run(carry)
            float(jax.tree.leaves(out)[0].ravel()[0])
            best = min(best, time.perf_counter() - t0)
        return best

    # pilot to size N for a ~1 s span, then one long measured run
    per = max(run_time(iters) / iters, 1e-7)
    n = int(min(max(1.0 / per, iters), 65536))
    return run_time(n) / n


def report(name, ours, base):
    print(f"{name:<38} ours {ours*1e3:8.3f} ms   baseline {base*1e3:8.3f} ms"
          f"   x{base/ours:.2f}")


def bench_fused_adam():
    """Chunked FusedAdam vs unfused optax.adam on a ~1M-param MLP pytree."""
    import optax

    from apex_tpu.optimizers import fused_adam

    key = jr.PRNGKey(0)
    # a realistic many-tensor pytree: 8 layers of (weight, bias)
    params = {}
    for i in range(8):
        k1, key = jr.split(key)
        params[f"w{i}"] = jr.normal(k1, (360, 360), jnp.float32)
        params[f"b{i}"] = jnp.zeros((360,), jnp.float32)

    ours_opt = fused_adam(learning_rate=1e-3)
    base_opt = optax.adam(1e-3)

    def bench(opt):
        # grads derive from the evolving params so every iteration does a
        # full, un-hoistable update
        def step(carry):
            params, state = carry
            grads = jax.tree.map(lambda x: x * 1e-3, params)
            updates, state = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        return timeit(step, (params, opt.init(params)))

    report("fused_adam vs optax.adam (1M params)", bench(ours_opt), bench(base_opt))


def bench_fused_lamb():
    import optax

    from apex_tpu.optimizers import fused_lamb

    key = jr.PRNGKey(1)
    params = {}
    for i in range(8):
        k1, key = jr.split(key)
        params[f"w{i}"] = jr.normal(k1, (360, 360), jnp.float32)
        params[f"b{i}"] = jnp.zeros((360,), jnp.float32)

    def bench(opt):
        def step(carry):
            params, state = carry
            grads = jax.tree.map(lambda x: x * 1e-3, params)
            updates, state = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        return timeit(step, (params, opt.init(params)))

    report("fused_lamb vs optax lamb (1M params)",
           bench(fused_lamb(learning_rate=1e-3)),
           bench(optax.lamb(1e-3)))


def bench_layer_norm():
    """Pallas LN fwd+bwd vs jnp composition, transformer-shaped input."""
    from apex_tpu.ops.layer_norm import fused_layer_norm

    x = jr.normal(jr.PRNGKey(2), (16 * 1024, 1024), jnp.bfloat16)
    g = jnp.ones((1024,), jnp.bfloat16)
    b = jnp.zeros((1024,), jnp.bfloat16)

    def ours_loss(x, g, b):
        return jnp.sum(fused_layer_norm(x, g, b).astype(jnp.float32))

    def base_loss(x, g, b):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + 1e-5) * g.astype(jnp.float32) + b.astype(jnp.float32)
        return jnp.sum(y)

    def bench(loss):
        gfn = jax.grad(loss, argnums=(0, 1, 2))

        def step(carry):
            # thread ALL inputs through the carry: dgamma/dbeta must be
            # consumed or XLA DCEs them (asymmetrically — an opaque Pallas
            # bwd can't be partially eliminated)
            x_, g_, b_ = carry
            gx, gg, gb = gfn(x_, g_, b_)
            return x_ - 1e-6 * gx, g_ - 1e-6 * gg, b_ - 1e-6 * gb

        return timeit(step, (x, g, b))

    report("fused layer_norm fwd+bwd (16k x 1024)",
           bench(ours_loss), bench(base_loss))


def bench_fused_dense_gelu_dense():
    """DenseGeluDense block vs naive chained jnp ops."""
    from apex_tpu.ops.fused_dense import fused_dense_gelu_dense

    H, F = 1024, 4096
    x = jr.normal(jr.PRNGKey(3), (16 * 128, H), jnp.bfloat16)
    # torch (out_features, in_features) convention, matching the module
    w1 = jr.normal(jr.PRNGKey(4), (F, H), jnp.bfloat16) * 0.02
    b1 = jnp.zeros((F,), jnp.bfloat16)
    w2 = jr.normal(jr.PRNGKey(5), (H, F), jnp.bfloat16) * 0.02
    b2 = jnp.zeros((H,), jnp.bfloat16)

    def ours_loss(x, w1, b1, w2, b2):
        return jnp.sum(fused_dense_gelu_dense(x, w1, b1, w2, b2).astype(jnp.float32))

    def base_loss(x, w1, b1, w2, b2):
        h = jax.nn.gelu(x @ w1.T + b1)
        return jnp.sum((h @ w2.T + b2).astype(jnp.float32))

    def bench(loss):
        gfn = jax.grad(loss, argnums=(0, 1, 2, 3, 4))

        def step(carry):
            x_, w1_, b1_, w2_, b2_ = carry
            gs = gfn(x_, w1_, b1_, w2_, b2_)
            return tuple(c - 1e-6 * g for c, g in zip(carry, gs))

        return timeit(step, (x, w1, b1, w2, b2))

    report("dense_gelu_dense fwd+bwd (2k x 1024x4096)",
           bench(ours_loss), bench(base_loss))


def bench_softmax_xentropy():
    """Fused softmax-CE vs naive log_softmax + gather (32k vocab)."""
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    logits = jr.normal(jr.PRNGKey(6), (8 * 1024, 32768), jnp.float32)
    labels = jr.randint(jr.PRNGKey(7), (8 * 1024,), 0, 32768)

    def ours_loss(logits, labels):
        return jnp.mean(softmax_cross_entropy_loss(logits, labels))

    def base_loss(logits, labels):
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))

    def bench(loss):
        gfn = jax.grad(loss)

        def step(lg):
            return lg - 1e-3 * gfn(lg, labels)

        return timeit(step, logits)

    report("softmax_xentropy fwd+bwd (8k x 32768)",
           bench(ours_loss), bench(base_loss))


def bench_multihead_attn():
    """SelfMultiheadAttn fwd+bwd vs the stock per-projection + materialized
    softmax composition — the analog of the reference's
    ``contrib/examples/multihead_attn/perf_test_multihead_attn.py``
    (seq 1024, embed 1024, 16 heads — beyond fmha's 512 cap)."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    E, H, B, S = 1024, 16, 8, 1024
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.0, bias=True)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                          m.init(jr.PRNGKey(8)))
    x = jr.normal(jr.PRNGKey(9), (B, S, E), jnp.bfloat16)

    def ours_loss(p, x):
        return jnp.sum(m(p, x, causal=True, is_training=False)
                       .astype(jnp.float32))

    def base_loss(p, x):
        qkv = x @ p["qkv_weight"].T + p["qkv_bias"]
        q, k, v = jnp.split(qkv, 3, -1)
        d = E // H

        def heads(t):
            return t.reshape(B, S, H, d).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / d ** 0.5
        mask = jnp.tril(jnp.ones((S, S), bool))
        probs = jax.nn.softmax(jnp.where(mask, s, -1e30), -1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, E)
        return jnp.sum((o @ p["out_weight"].T + p["out_bias"])
                       .astype(jnp.float32))

    def bench(loss):
        gfn = jax.grad(loss)

        def step(p):
            g = gfn(p, x)
            return jax.tree.map(lambda a, b: a - 1e-6 * b, p, g)

        return timeit(step, params, iters=16)

    report("self_multihead_attn fwd+bwd (8x1024)",
           bench(ours_loss), bench(base_loss))


def main():
    os.environ.setdefault("APEX_TPU_PALLAS", "1")
    print(f"backend: {jax.default_backend()} "
          f"({jax.devices()[0].device_kind})")
    bench_fused_adam()
    bench_fused_lamb()
    bench_layer_norm()
    bench_fused_dense_gelu_dense()
    bench_softmax_xentropy()
    bench_multihead_attn()


if __name__ == "__main__":
    main()
