"""One-off sweep: does the lighter vocab-parallel-CE residual (logits+stats
instead of fp32 softmax) unlock remat=False or batch 32 on the flagship
bench shape? Prints ms/step per config."""
import os
import sys
import time

import jax
import jax.numpy as jnp
import jax.random as jr

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import build


def run(tag, cfg, batch, iters=8):
    tokens = jr.randint(jr.PRNGKey(1), (batch, 1024), 0, cfg["vocab_size"])
    targets = jr.randint(jr.PRNGKey(2), (batch, 1024), 0, cfg["vocab_size"])
    try:
        step, params, opt_state = build("fused", cfg, donate=True)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, tokens, targets)
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        print(f"{tag}: {dt*1e3:.1f} ms/step  {batch*1024/dt:,.0f} tok/s")
    except Exception as e:
        print(f"{tag}: FAILED {type(e).__name__}: {str(e)[:160]}")


BASE = dict(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
            num_layers=12, num_heads=16, tp_size=1, remat=True,
            attention_impl="flash", remat_policy="mlp_only",
            scan_layers=False)

if __name__ == "__main__":
    import os
    os.environ["APEX_TPU_PALLAS"] = "1"
    run("b16 remat=mlp_only", BASE, 16)
    run("b16 remat=False", dict(BASE, remat=False), 16)
    run("b32 remat=mlp_only", BASE, 32)
    run("b32 remat=False", dict(BASE, remat=False), 32)
