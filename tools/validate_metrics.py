#!/usr/bin/env python
"""Validate monitor JSONL streams and bench/gate JSON artifacts.

Usage::

    python tools/validate_metrics.py events.jsonl BENCH_r05.json ...
    python tools/validate_metrics.py --lint-report lint.json ...
    python tools/validate_metrics.py --costdb costdb.json ...
    python tools/validate_metrics.py --profile profile.jsonl ...
    python tools/validate_metrics.py --serve serve.jsonl ...
    python tools/validate_metrics.py --serve-window windows.jsonl ...
    python tools/validate_metrics.py --pipeline pipeline.jsonl ...
    python tools/validate_metrics.py --static-cost static_cost.jsonl ...
    python tools/validate_metrics.py --static-memory static_memory.jsonl ...
    python tools/validate_metrics.py --plan plan.jsonl ...
    python tools/validate_metrics.py --serve-plan serve_plan.jsonl ...
    python tools/validate_metrics.py --ckpt ckpt.jsonl ...
    python tools/validate_metrics.py --spec spec.jsonl ...
    python tools/validate_metrics.py --tp-serve tp_serve.jsonl ...
    python tools/validate_metrics.py --trace flight-dump.json ...

Dispatch is by content, not extension:

* ``.jsonl`` files (or any file whose first non-blank line parses as a
  JSON object with a ``kind``) validate as a monitor event stream against
  :mod:`apex_tpu.monitor.schema` — including ``decode`` serving-bench
  records (``python bench.py --decode``), ``longseq_bias`` records
  (``python bench.py --longseq-bias``: in-kernel bucketed bias vs the
  materialized baseline) and ``tp_overlap`` records (``python bench.py
  --tp-overlap``: ring-overlapped vs blocking TP boundary collectives),
  whose ``status: "OK"`` engages the same no-nan honesty rule as gates
  (and whose SKIP must carry a reason);
* bench result objects (``{"metric": ..., "value": ...}``) validate
  against the BENCH schema;
* driver wrappers are unwrapped: ``{"parsed": {...}}`` (BENCH_r*.json)
  validates the inner result; ``{"ok": ..., "tail": ...}``
  (MULTICHIP_r*.json) additionally enforces the artifact-honesty rule on
  the captured gate output — an OK line carrying ``=nan``/``=inf`` fails
  (VERDICT r5 weak #1), and any embedded ``MULTICHIP_GATE`` JSON record is
  schema-validated;
* apexlint reports (``python -m apex_tpu.lint --format json``, shape
  ``{"tool": "apexlint", ...}``) validate against
  ``apex_tpu.lint.validate_report`` — so the lint artifact is gated the
  same way bench/gate artifacts are. Well-formed lint reports are
  auto-detected, so mixing them with bench/gate files in one invocation
  just works; ``--lint-report`` instead forces EVERY listed file to be
  judged as a lint report (a malformed file that lost its ``tool`` key
  must fail as a bad lint report, not as an unrecognized shape) — don't
  combine it with non-lint artifacts;
* ``profile`` records (``python bench.py --profile``: the step-anatomy
  leg), ``serve`` records (``python bench.py --serve``: the
  continuous-batching offered-load leg through the paged
  ``apex_tpu.serving`` engine — incl. the serving-tier-2 fields:
  ``prefix_hit_rate``, the hit/miss TTFT split, ``preemptions``,
  ``recompute_tokens``, ``churn_parity``, ``trace_seed``),
  ``serve_event``/``serve_window`` records (the request-lifecycle —
  now with the live ``evict`` payload — and live-SLO telemetry of
  ``apex_tpu.serving.telemetry``), ``pipeline`` records (``python
  bench.py --pipeline``: the zero-bubble-vs-1f1b schedule leg),
  ``costdb`` artifacts (``apex_tpu.prof.calibrate``), and
  ``static_cost`` artifacts (``python -m apex_tpu.lint --jaxpr
  --static-cost``: the jaxpr walker's predicted per-collective bytes /
  per-GEMM FLOPs — the planner's predicted side of the CostDB diff),
  and ``static_memory`` artifacts (``python -m apex_tpu.lint --jaxpr
  --memory --static-memory``: the apexmem donation-aware liveness
  peak-HBM bound with its family breakdown — a CLOSED schema with
  integer byte fields, so a junk key or a nan-shaped peak fails),
  and ``plan`` records (``python bench.py --plan``: the auto-
  parallelism planner's searched ranking + chosen ParallelPlan +
  predicted-vs-measured error — plan objects and ranking rows are
  closed schemas, so a junk key fails), and ``ckpt`` records
  (``python bench.py --ckpt``: the elastic-checkpoint save-cost leg —
  its ``manifest`` section is a closed schema, so a junk manifest key
  fails), and ``spec`` records (``python bench.py --spec``: the
  speculative-decoding + quantized-KV leg — a CLOSED schema, so a junk
  key fails, and its OK line engages the no-nan honesty rule like
  every status record), and ``tp_serve`` records (``python bench.py
  --serve --plan-tp N``: the tensor-parallel serving + disaggregated
  prefill→decode handoff leg — a CLOSED schema whose OK line is a
  real-multichip-TPU claim; off-TPU it must be a reasoned SKIP),
  and ``serve_plan`` records (``python bench.py --serve --plan-serve``:
  the trace-replay-priced serving-knob search — the chosen ServePlan
  and every ranking row are CLOSED schemas, so a junk key fails; an OK
  line engages the no-nan honesty rule and a SKIP needs a reason)
  dispatch on ``kind`` like every monitor record. ``--profile`` /
  ``--serve`` / ``--serve-window`` / ``--serve-plan`` / ``--tp-serve`` /
  ``--pipeline`` /
  ``--costdb`` / ``--static-cost`` / ``--static-memory`` / ``--plan`` /
  ``--ckpt`` / ``--spec`` force EVERY listed file to be judged as that
  artifact
  (same rationale as ``--lint-report``: an artifact that lost its
  ``kind`` key must fail as a bad
  profile/serve/pipeline/costdb/static_cost/plan/ckpt/spec/tp_serve/
  serve_plan, not as an unrecognized shape). ``--trace`` forces the request-scoped
  tracing FAMILY (``serve_attribution`` / ``clock_sync`` /
  ``flight_recorder_dump`` — all closed schemas): a single object must
  be one of the three, a stream must contain at least one.

Exit status 0 when every file is clean; 1 otherwise, with one problem per
line on stderr. The logic lives in ``apex_tpu.monitor.schema`` so tests
and the emitter share it; this file is the CLI.
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.monitor import schema  # noqa: E402

# a token like loss=nan / ring_vs_flash=inf inside a success line
_NAN_TOKEN = re.compile(r"=\s*(nan|[+-]?inf(inity)?)\b", re.IGNORECASE)


def check_gate_tail(tail: str) -> list:
    """Honesty scan of captured gate stdout: success lines must not carry
    non-finite metric tokens, and embedded MULTICHIP_GATE records must
    validate."""
    problems = []
    for line in tail.splitlines():
        stripped = line.strip()
        if stripped.startswith("MULTICHIP_GATE "):
            try:
                record = json.loads(stripped[len("MULTICHIP_GATE "):])
            except json.JSONDecodeError as e:
                problems.append(f"embedded gate record is invalid JSON: {e}")
                continue
            problems.extend(f"embedded gate record: {err}"
                            for err in schema.validate(record))
        elif stripped.endswith(" OK") or stripped == "OK":
            if _NAN_TOKEN.search(stripped):
                problems.append(
                    f"OK line carries a non-finite metric token: {stripped!r}")
    return problems


def validate_lint_report(obj) -> list:
    """Validate an apexlint ``--format json`` report."""
    from apex_tpu.lint import validate_report
    return validate_report(obj)


def validate_object(obj) -> list:
    """Validate one JSON artifact object, unwrapping driver envelopes."""
    if isinstance(obj, dict) and obj.get("tool") == "apexlint":
        return validate_lint_report(obj)
    if isinstance(obj, dict) and "kind" in obj:
        return schema.validate(obj)
    if isinstance(obj, dict) and "metric" in obj:
        return schema.validate(obj, schema.BENCH_SCHEMA)
    if isinstance(obj, dict) and isinstance(obj.get("parsed"), dict):
        return [f"parsed: {e}"
                for e in schema.validate(obj["parsed"], schema.BENCH_SCHEMA)]
    if isinstance(obj, dict) and "tail" in obj:
        if obj.get("ok") or obj.get("rc") == 0:
            return check_gate_tail(str(obj["tail"]))
        return []  # failed runs may contain anything; they claim nothing
    return ["unrecognized artifact shape (no kind/metric/parsed/tail)"]


def validate_file(path: str, *, as_lint_report: bool = False,
                  force_kind=None) -> list:
    problems = []
    with open(path) as fh:
        text = fh.read()
    if as_lint_report:
        try:
            obj = json.loads(text)
        except json.JSONDecodeError as e:
            return [f"{path}: not JSON: {e}"]
        return [f"{path}: {e}" for e in validate_lint_report(obj)]
    if force_kind is not None:
        # --profile / --costdb / --trace: judge the file as that
        # artifact kind (or kind FAMILY — --trace accepts any of the
        # tracing records) — one JSON object, or a JSONL stream that
        # must CONTAIN one of the kinds
        family = (force_kind if isinstance(force_kind, tuple)
                  else (force_kind,))
        want = " or ".join(repr(k) for k in family)
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            obj = None
        if isinstance(obj, dict):
            if obj.get("kind") not in family:
                return [f"{path}: expected a {want} artifact, "
                        f"got kind={obj.get('kind')!r}"]
            return [f"{path}: {e}" for e in schema.validate(obj)]
        problems = [f"{path}:{lineno}: {err}"
                    for lineno, err in schema.validate_jsonl(
                        text.splitlines())]
        kinds = set()
        for line in text.splitlines():
            line = line.strip()
            if line:
                try:
                    kinds.add(json.loads(line).get("kind"))
                except json.JSONDecodeError:
                    pass
        if not kinds.intersection(family):
            problems.append(
                f"{path}: stream carries no {want} record")
        return problems
    # one JSON value in the whole file → single artifact; otherwise JSONL
    obj = None
    if not path.endswith(".jsonl"):
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            obj = None
    if obj is None:
        for lineno, err in schema.validate_jsonl(text.splitlines()):
            problems.append(f"{path}:{lineno}: {err}")
        return problems
    problems.extend(f"{path}: {e}" for e in validate_object(obj))
    return problems


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_lint = "--lint-report" in argv
    force_kind = None
    if "--costdb" in argv:
        force_kind = "costdb"
    elif "--profile" in argv:
        force_kind = "profile"
    elif "--serve-plan" in argv:
        force_kind = "serve_plan"
    elif "--serve-window" in argv:
        force_kind = "serve_window"
    elif "--tp-serve" in argv:
        force_kind = "tp_serve"
    elif "--serve" in argv:
        force_kind = "serve"
    elif "--pipeline" in argv:
        force_kind = "pipeline"
    elif "--static-memory" in argv:
        force_kind = "static_memory"
    elif "--static-cost" in argv:
        force_kind = "static_cost"
    elif "--plan" in argv:
        force_kind = "plan"
    elif "--ckpt" in argv:
        force_kind = "ckpt"
    elif "--spec" in argv:
        force_kind = "spec"
    elif "--trace" in argv:
        # the request-scoped tracing family: an attribution summary, a
        # clock_sync stamp, or a flight-recorder dump all count
        force_kind = ("serve_attribution", "clock_sync",
                      "flight_recorder_dump")
    argv = [a for a in argv
            if a not in ("--lint-report", "--costdb", "--profile",
                         "--serve", "--serve-window", "--serve-plan",
                         "--tp-serve",
                         "--pipeline", "--static-cost", "--static-memory",
                         "--plan", "--ckpt", "--spec", "--trace")]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    all_problems = []
    for path in argv:
        all_problems.extend(validate_file(path, as_lint_report=as_lint,
                                          force_kind=force_kind))
    for problem in all_problems:
        print(problem, file=sys.stderr)
    if not all_problems:
        print(f"{len(argv)} artifact(s) valid")
    return 1 if all_problems else 0


if __name__ == "__main__":
    sys.exit(main())
