"""T5 relative-bias long-sequence A/B on one chip (PERF.md "T5 relative
bias on flash (r5)"): flash (in-kernel bias operand) vs softmax
(materialized (b,h,s,s) scores) at s=2048, T5-base-class shape.

Usage: python tools/t5_relative_bench.py [impl] [batch] [seq]
"""
import sys
import time

import jax
import jax.numpy as jnp
import jax.random as jr
import optax

from apex_tpu.models import EncoderDecoderModel, T5Config

impl = sys.argv[1] if len(sys.argv) > 1 else "flash"
batch = int(sys.argv[2]) if len(sys.argv) > 2 else 8
seq = int(sys.argv[3]) if len(sys.argv) > 3 else 2048

cfg = T5Config(vocab_size=32128, max_seq_len=seq, hidden_size=768,
               ffn_hidden_size=3072, num_encoder_layers=12,
               num_decoder_layers=12, num_heads=6, dtype=jnp.bfloat16,
               attention_impl=impl, position_encoding="relative",
               remat=True, remat_policy="blocks")
m = EncoderDecoderModel(cfg)
params = m.init(jr.PRNGKey(0))
opt = optax.adam(1e-4)

enc = jr.randint(jr.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)
dec = jr.randint(jr.PRNGKey(2), (batch, seq), 0, cfg.vocab_size)
tgt = jr.randint(jr.PRNGKey(3), (batch, seq), 0, cfg.vocab_size)


@jax.jit
def step(params, opt_state):
    loss, g = jax.value_and_grad(m.loss_fn)(params, enc, dec, tgt)
    u, opt_state = opt.update(g, opt_state)
    return optax.apply_updates(params, u), opt_state, loss


opt_state = opt.init(params)
params, opt_state, loss = step(params, opt_state)
print("warm loss", float(loss))
iters = 5
times = []
for _ in range(2):
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state)
    float(loss)
    times.append((time.perf_counter() - t0) / iters)
ms = min(times) * 1e3
print(f"impl={impl} b={batch} s={seq}: {ms:.1f} ms/step, "
      f"{batch * seq / min(times):.0f} dec tok/s")
