"""Hardware rows for PERF.md (r3):

1. fp16-strict flagship variant: the GPT-medium bench step under
   half_dtype=float16 with fp32 master weights + the DYNAMIC loss scaler —
   the scaler's skip/recover path at training scale on the real chip, plus
   the throughput cost vs bf16.
2. ring vs Ulysses context parallelism at seq >= 8192 — single-chip
   kernel-path timing (the collectives are identity at cp=1, so this
   isolates the compute formulations; cross-device parity is covered by
   the cp=4 CPU-mesh tests and the driver gate).

Run: python tools/fp16_and_cp_bench.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.random as jr


def fp16_flagship():
    import optax

    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import fused_adam

    cfg = GPTConfig(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                    num_layers=12, num_heads=8, remat=False,
                    attention_impl="flash", scan_layers=False)
    model = GPTModel(cfg)
    policy = amp.get_policy("O2", half_dtype=jnp.float16)
    params = model.init(jr.PRNGKey(0))
    master = amp.MasterWeights.create(params, policy)
    opt = amp.skip_step_if_nonfinite(fused_adam(learning_rate=1e-4))
    opt_state = opt.init(master.master)
    scaler = amp.init_loss_scaler("dynamic")
    batch, seq = 16, 1024
    tokens = jr.randint(jr.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)
    targets = jr.randint(jr.PRNGKey(2), (batch, seq), 0, cfg.vocab_size)

    def loss_fn(p, tokens, targets):
        return model.loss_fn(p, tokens, targets)

    def step(master, opt_state, scaler, tokens, targets):
        loss, (grads, finite, scaler) = amp.scaled_value_and_grad(loss_fn)(
            scaler, master.model, tokens, targets)
        updates, opt_state = opt.update(grads, opt_state, master.master)
        master = amp.apply_updates_with_master(
            master, updates, grads_finite=finite)
        return master, opt_state, scaler, loss

    f = jax.jit(step, donate_argnums=(0, 1))
    scales = []
    master, opt_state, scaler, loss = f(master, opt_state, scaler, tokens,
                                        targets)
    master, opt_state, scaler, loss = f(master, opt_state, scaler, tokens,
                                        targets)
    float(loss)
    t0 = time.perf_counter()
    iters = 20
    for i in range(iters):
        master, opt_state, scaler, loss = f(master, opt_state, scaler,
                                            tokens, targets)
        if i % 5 == 0:
            scales.append(float(scaler.loss_scale))
    lv = float(loss)
    dt = (time.perf_counter() - t0) / iters
    # note: the in-loop scale fetches sync the chain; re-time clean
    master, opt_state, scaler, loss = f(master, opt_state, scaler, tokens,
                                        targets)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        master, opt_state, scaler, loss = f(master, opt_state, scaler,
                                            tokens, targets)
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    print(f"fp16-strict flagship: {batch * seq / dt:,.0f} tok/s "
          f"({dt * 1e3:.1f} ms/step)  loss={lv:.3f}  "
          f"skipped={int(scaler.skipped_steps)}  "
          f"scale trajectory={scales} -> {float(scaler.loss_scale):.0f}")


def cp_long_seq():
    from apex_tpu.ops.attention import (flash_attention, ring_attention,
                                        ulysses_attention, zigzag_shard)
    from apex_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.initialize_model_parallel()  # 1 chip: cp=1 identity
    b, h, s, d = 4, 8, 8192, 128

    q = jr.normal(jr.PRNGKey(3), (b * h, s, d), jnp.bfloat16)

    def time_fn(f, *args):
        g = jax.jit(lambda *a: jnp.sum(
            jax.grad(lambda *aa: jnp.sum(f(*aa).astype(jnp.float32)))(
                *a).astype(jnp.float32)))
        g(*args)
        x = g(*args)
        float(x)
        t0 = time.perf_counter()
        for _ in range(5):
            x = g(*args)
        float(x)
        return (time.perf_counter() - t0) / 5 * 1e3

    t_flash = time_fn(lambda q: flash_attention(q, q, q, causal=True), q)

    from jax.sharding import PartitionSpec as P
    qz = zigzag_shard(q, 1, 1)

    def ring(qq):
        return mesh_lib.shard_map(
            lambda q: ring_attention(q, q, q, causal=True),
            mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp"),
        )(qq)

    t_ring = time_fn(ring, qz)

    q4 = q.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    def uly(qq):
        return mesh_lib.shard_map(
            lambda q: ulysses_attention(q, q, q, causal=True),
            mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp"),
        )(qq)

    t_uly = time_fn(uly, q4)
    print(f"seq {s} fwd+bwd (bh={b * h}, d={d}, 1 chip): "
          f"flash {t_flash:.1f} ms  ring(cp=1) {t_ring:.1f} ms  "
          f"ulysses(cp=1) {t_uly:.1f} ms")
    mesh_lib.destroy_model_parallel()


if __name__ == "__main__":
    fp16_flagship()
    cp_long_seq()
