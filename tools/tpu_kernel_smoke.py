import sys
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))
import numpy as np
import jax, jax.numpy as jnp
import jax.random as jr

k = jr.PRNGKey(0)
ok = []

def check(name, fn, *args):
    try:
        out = jax.jit(fn)(*args)
        np.asarray(jax.tree.leaves(out)[0])  # host fetch = reliable sync
        ok.append(name)
        print(f"PASS {name}")
    except Exception as e:
        print(f"FAIL {name}: {str(e)[:300]}")

# layer norm fwd+bwd, bf16 weights (the GPT bench path)
from apex_tpu.ops import fused_layer_norm, fused_rms_norm
x = jr.normal(k, (512, 1024), jnp.bfloat16)
w = jnp.ones((1024,), jnp.bfloat16); b = jnp.zeros((1024,), jnp.bfloat16)
check("ln fwd", lambda x, w, b: fused_layer_norm(x, w, b, impl="pallas"), x, w, b)
check("ln bwd", jax.grad(lambda x, w, b: fused_layer_norm(x, w, b, impl="pallas").astype(jnp.float32).sum(), argnums=(0, 1, 2)), x, w, b)
check("rms bwd", jax.grad(lambda x, w: fused_rms_norm(x, w, impl="pallas").astype(jnp.float32).sum(), argnums=(0, 1)), x, w)

# softmax
from apex_tpu.ops import scaled_upper_triang_masked_softmax, scaled_masked_softmax
s = jr.normal(k, (8, 256, 256), jnp.bfloat16)
check("causal softmax fwd+bwd", jax.grad(lambda s: scaled_upper_triang_masked_softmax(s, 0.125, impl="pallas").astype(jnp.float32).sum()), s)
mask = jnp.zeros((8, 256, 256), bool)
check("masked softmax", lambda s: scaled_masked_softmax(s, mask, 0.125, impl="pallas"), s)

# matmul bias act
from apex_tpu.ops import fused_dense, fused_dense_gelu_dense, mlp
xd = jr.normal(k, (1024, 1024), jnp.bfloat16)
wd = jr.normal(k, (4096, 1024), jnp.bfloat16) * 0.02
bd = jnp.zeros((4096,), jnp.bfloat16)
check("fused_dense fwd", lambda x, w, b: fused_dense(x, w, b, impl="pallas"), xd, wd, bd)
check("fused_dense bwd", jax.grad(lambda x, w, b: fused_dense(x, w, b, impl="pallas").astype(jnp.float32).sum(), argnums=(0, 1, 2)), xd, wd, bd)
w2 = jr.normal(k, (1024, 4096), jnp.bfloat16) * 0.02
b2 = jnp.zeros((1024,), jnp.bfloat16)
check("dgd bwd", jax.grad(lambda x: fused_dense_gelu_dense(x, wd, bd, w2, b2, impl="pallas").astype(jnp.float32).sum()), xd)
check("mlp bwd", jax.grad(lambda x: mlp(x, [wd], [bd], "relu", impl="pallas").astype(jnp.float32).sum()), xd)

# flash attention
from apex_tpu.ops.attention import flash_attention, fused_qkv_attention
q = jr.normal(k, (8, 512, 64), jnp.bfloat16)
check("flash fwd", lambda q: flash_attention(q, q, q, causal=True, impl="pallas"), q)
check("flash bwd", jax.grad(lambda q: flash_attention(q, q, q, causal=True, impl="pallas").astype(jnp.float32).sum()), q)

# seq-major (bshd) + fused attention block (the r3 zero-copy flagship path)
qb = jr.normal(k, (2, 512, 4, 128), jnp.bfloat16)
check("flash bshd fwd", lambda q: flash_attention(
    q, q, q, causal=True, impl="pallas", layout="bshd"), qb)
check("flash bshd bwd", jax.grad(lambda q: flash_attention(
    q, q, q, causal=True, impl="pallas",
    layout="bshd").astype(jnp.float32).sum()), qb)
xf = jr.normal(k, (2, 512, 512), jnp.bfloat16)
wqkv = jr.normal(k, (3 * 4 * 128, 512), jnp.bfloat16) * 0.02
bqkv = jnp.zeros((3 * 4 * 128,), jnp.bfloat16)
wout = jr.normal(k, (512, 4 * 128), jnp.bfloat16) * 0.02
check("fused_qkv_attention fwd", lambda x: fused_qkv_attention(
    x, wqkv, bqkv, wout, None, None, None, 4, 4, 128, 128 ** -0.5, True),
    xf)
check("fused_qkv_attention bwd", jax.grad(lambda x: fused_qkv_attention(
    x, wqkv, bqkv, wout, None, None, None, 4, 4, 128, 128 ** -0.5,
    True).astype(jnp.float32).sum()), xf)
check("fused_qkv_attention dropout fwd", lambda x: fused_qkv_attention(
    x, wqkv, bqkv, wout, None, jnp.int32(7), None, 4, 4, 128, 128 ** -0.5,
    True, 0.1), xf)
biash = jr.normal(k, (4, 512, 512), jnp.float32) * 0.5
check("fused_qkv_attention bias bwd", jax.grad(lambda x: fused_qkv_attention(
    x, wqkv, bqkv, wout, biash, None, None, 4, 4, 128, 128 ** -0.5,
    True).astype(jnp.float32).sum()), xf)
check("flash bias bwd", jax.grad(lambda q: flash_attention(
    q, q, q, causal=True, impl="pallas",
    bias=biash[:1, :, :]).astype(jnp.float32).sum()), q)
check("flash dropout bwd", jax.grad(lambda q: flash_attention(
    q, q, q, causal=True, impl="pallas", dropout_rate=0.1,
    dropout_seed=jnp.int32(7)).astype(jnp.float32).sum()), q)

# fused optimizers (multi-tensor engine)
from apex_tpu.optimizers import fused_adam, fused_lamb, fused_sgd
params = {"a": jr.normal(k, (1024, 1024)), "b": jr.normal(k, (333,))}
grads = jax.tree.map(lambda p: p * 0.01, params)
for name, ctor in [("adam", fused_adam), ("lamb", fused_lamb), ("sgd", fused_sgd)]:
    opt = ctor(learning_rate=1e-3) if name != "sgd" else ctor(learning_rate=1e-3, momentum=0.9)
    st = opt.init(params)
    check(f"fused_{name}", lambda g, s, p: opt.update(g, s, p), grads, st, params)

print(f"{len(ok)} kernels pass on", jax.devices()[0].device_kind)
