#!/usr/bin/env python
"""Regression gate: compare a fresh bench/serve artifact against the
checked-in ``BENCH_r*.json`` trajectory.

Usage::

    python tools/bench_history.py fresh.json
    python tools/bench_history.py fresh.json --history 'BENCH_r*.json'
    python tools/bench_history.py fresh.json --tolerance-pct 5
    python tools/bench_history.py --schema-only fresh.json

The driver stores one ``BENCH_r<N>.json`` envelope per PR (``{"parsed":
{"metric": ..., "value": ..., "spread_pct": ...}}``). This tool turns
that trajectory into a gate a CI leg can run after a fresh bench:

* **Extraction** understands every throughput artifact the repo emits:
  bench result objects (``{"metric", "value", ...}``), driver envelopes
  (``{"parsed": {...}}``), and monitor records with a throughput field
  (``serve`` / ``decode`` / ``tp_overlap`` / ``pipeline`` /
  ``tp_serve`` → ``tokens_per_s``). An OK ``serve`` record additionally
  carries its ``prefix_hit_ttft_p50_ms`` as a LOWER-is-better latency
  series (the serving-tier-2 headline: a prefix hit must stay fast
  across the trajectory); an OK ``tp_serve`` record carries its
  ``handoff_transfer_ms`` the same lower-is-better way (the
  disaggregated KV stream must not slow down). An OK ``plan`` record
  carries its step-time ``predicted_vs_measured_err_pct`` and — when
  ``memory_stats()`` measured one — the apexmem
  ``predicted_vs_measured_hbm_err_pct``, both gated in absolute points
  (a healthy model's reference is ~0). An OK ``serve_plan`` record
  (``bench.py --serve --plan-serve``) carries the searched plan's
  measured ``serve_plan_tokens_per_s`` (higher-is-better) and the
  replay model's ``serve_plan_predicted_vs_measured_err_pct``
  (lower-is-better, absolute points); pre-ServePlan history artifacts
  carry neither, so the new series SKIP individually. An OK ``spec`` record carries TWO higher-is-better
  series: ``spec_tokens_per_s_request`` (the speculative-decoding
  headline) and ``spec_acceptance_rate`` (the drafter-quality series
  that explains it — a silent acceptance collapse would eventually
  surface as a throughput regression anyway, but gating it directly
  names the cause); a record from a ``--spec --tree`` run additionally
  carries ``tree_spec_tokens_per_s_request`` and
  ``tree_spec_acceptance_rate``, gated the same higher-is-better way
  (pre-tree history SKIPs the new series only — the established chain
  series still gate). History artifacts that predate a series simply
  carry no point for it, so a fresh record's NEW series SKIP
  individually while its established ones still gate. A ``status:
  "SKIP"`` record carries no claim and is *skipped* by the gate
  (exit 0 with a SKIP line) — an off-TPU smoke can never "regress".
* **Comparison** is against the LATEST history artifact whose metric
  name matches the fresh one (the trajectory's newest point — the
  number the README quotes). The allowance is
  ``tolerance_pct + spread_pct(history) + spread_pct(fresh)``:
  run-to-run noise measured by the artifacts themselves widens the
  band, a silent slowdown beyond it fails.
* **Verdict** is one line — ``OK``, ``SKIP`` or ``REGRESSION`` with
  the percentage delta vs the allowance — and the exit code: 0 clean
  or nothing to compare, 1 regression, 2 usage/parse errors.

``--schema-only`` validates the fresh artifact and the history through
``apex_tpu.monitor.schema`` without comparing (the off-TPU tier-1
smoke: the gate's plumbing is exercised on every run even where a
throughput claim would be dishonest).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.monitor import schema  # noqa: E402

# monitor-record kinds that carry a tokens_per_s throughput claim
_THROUGHPUT_KINDS = ("serve", "decode", "tp_overlap", "pipeline",
                     "tp_serve")

# metrics where a BIGGER fresh value is the regression, gated in
# ABSOLUTE points (error series — the reference may legitimately be ~0)
_LOWER_IS_BETTER = {"plan_predicted_vs_measured_err_pct",
                    # the serving planner's honesty series: the
                    # trace-replay model's predicted tokens/s vs the
                    # measured serve — same absolute-points rule as the
                    # training planner (healthy is near 0, so percent
                    # drift against ~0 is noise)
                    "serve_plan_predicted_vs_measured_err_pct",
                    # apexmem's memory honesty series: the liveness
                    # bound's error vs the device's measured peak HBM —
                    # a healthy model sits near 0, so percent drift
                    # against ~0 is noise; gate in absolute points
                    "plan_predicted_vs_measured_hbm_err_pct",
                    # async checkpointing's per-step cost: already a
                    # percentage of a step, and a healthy async saver
                    # sits near 0 — percent-drift against ~0 is noise
                    "ckpt_save_overhead_pct",
                    # request-lifecycle telemetry's measured cost as a
                    # fraction of the serve wall (the <1% budget): the
                    # same absolute-points rule — healthy is near 0
                    "serve_telemetry_overhead_pct"}

# lower-is-better metrics gated by PERCENT drift (latency series: the
# prefix-hit TTFT p50 must not creep up across the trajectory — the
# serving tier-2 headline is that a hit stays fast)
_LOWER_IS_BETTER_PCT = {"serve_prefix_hit_ttft_p50_ms",
                        # the disaggregated handoff's export→ingest
                        # wall: the KV stream must not slow down across
                        # the trajectory (creep here eats straight into
                        # the decode role's time-to-first-decode)
                        "tp_serve_handoff_transfer_ms"}

# hard absolute ceilings on top of trajectory drift: a fresh value over
# its budget fails EVEN IF the history crept up alongside it (drift
# gates catch jumps; budgets catch slow boil)
_ABSOLUTE_BUDGET = {"serve_telemetry_overhead_pct": 1.0}


def extract_all(obj: Dict[str, Any], label: str = "artifact"
                ) -> List[Tuple[str, float, float]]:
    """Every gated ``(metric_name, value, spread_pct)`` series one
    artifact carries — empty when it claims nothing (SKIP records,
    meta). An OK ``serve`` record carries its throughput AND, when the
    prefix cache measured one, the hit-TTFT latency series. Raises
    ValueError on a shape that should carry a claim but doesn't."""
    if not isinstance(obj, dict):
        raise ValueError(f"{label}: expected a JSON object")
    if isinstance(obj.get("parsed"), dict):  # driver envelope
        return extract_all(obj["parsed"], label)
    if "metric" in obj and "value" in obj:
        spread = obj.get("spread_pct")
        return [(str(obj["metric"]), float(obj["value"]),
                 float(spread) if isinstance(spread, (int, float))
                 else 0.0)]
    kind = obj.get("kind")
    if kind in _THROUGHPUT_KINDS:
        if obj.get("status") == "SKIP":
            return []  # a SKIP record claims nothing to regress from
        v = obj.get("tokens_per_s")
        if not isinstance(v, (int, float)):
            raise ValueError(
                f"{label}: OK {kind} record has no numeric tokens_per_s")
        spread = obj.get("spread_pct")
        spread = float(spread) if isinstance(spread, (int, float)) else 0.0
        rows = [(f"{kind}_tokens_per_s", float(v), spread)]
        if kind == "serve":
            # the prefix-cache latency series (absent on pre-tier-2
            # records and when no hit landed — a skip object, not 0).
            # spread_pct is the record's THROUGHPUT variance; it says
            # nothing about TTFT variance, so it must not widen the
            # latency gate
            hit = obj.get("prefix_hit_ttft_p50_ms")
            if isinstance(hit, (int, float)):
                rows.append(("serve_prefix_hit_ttft_p50_ms",
                             float(hit), 0.0))
            # the telemetry-cost series (absent on pre-tracing records):
            # gated in absolute points against the 1% budget — creeping
            # instrumentation must show up as a regression, and the
            # throughput spread says nothing about it
            ovh = obj.get("telemetry_overhead_pct")
            if isinstance(ovh, (int, float)):
                rows.append(("serve_telemetry_overhead_pct",
                             float(ovh), 0.0))
        if kind == "tp_serve":
            # the disaggregated handoff's transfer wall (absent on a
            # record that skipped the handoff leg — a skip, not 0):
            # lower-is-better percent drift; the record's spread_pct is
            # throughput variance and says nothing about transfer time
            tms = obj.get("handoff_transfer_ms")
            if isinstance(tms, (int, float)):
                rows.append(("tp_serve_handoff_transfer_ms",
                             float(tms), 0.0))
        return rows
    if kind == "plan":
        # the planner record's gated series is its predicted-vs-measured
        # ERROR (an OK record always carries one; the measured half only
        # skips inside SKIP records)
        if obj.get("status") == "SKIP":
            return []
        v = obj.get("predicted_vs_measured_err_pct")
        if not isinstance(v, (int, float)):
            raise ValueError(
                f"{label}: OK plan record has no numeric "
                "predicted_vs_measured_err_pct")
        rows = [("plan_predicted_vs_measured_err_pct", float(v), 0.0)]
        # the apexmem memory series (absent on pre-liveness records and
        # when memory_stats() skipped — a skip object, not 0): the
        # liveness peak-HBM bound vs the device's measured peak, gated
        # in absolute points like the step-time error
        hbm = obj.get("predicted_vs_measured_hbm_err_pct")
        if isinstance(hbm, (int, float)):
            rows.append(("plan_predicted_vs_measured_hbm_err_pct",
                         float(hbm), 0.0))
        return rows
    if kind == "spec":
        # the speculative-decoding leg: per-request throughput is the
        # headline, the acceptance rate the tracked drafter-quality
        # series (both higher-is-better). Pre-spec history artifacts
        # carry neither series — the per-series comparison SKIPs them
        # individually, never the whole gate.
        if obj.get("status") == "SKIP":
            return []
        v = obj.get("tokens_per_s_request")
        if not isinstance(v, (int, float)):
            raise ValueError(
                f"{label}: OK spec record has no numeric "
                "tokens_per_s_request")
        spread = obj.get("spread_pct")
        spread = float(spread) if isinstance(spread, (int, float)) else 0.0
        rows = [("spec_tokens_per_s_request", float(v), spread)]
        rate = obj.get("acceptance_rate")
        if isinstance(rate, (int, float)):
            # the record's spread_pct is throughput variance; it says
            # nothing about acceptance variance
            rows.append(("spec_acceptance_rate", float(rate), 0.0))
        # the tree-speculation series (absent on pre-tree records and on
        # --spec runs without --tree — a skip object, not 0): per-request
        # tree throughput plus the tree acceptance rate, both
        # higher-is-better like their chain counterparts
        tv = obj.get("tree_spec_tokens_per_s_request")
        if isinstance(tv, (int, float)):
            rows.append(("tree_spec_tokens_per_s_request", float(tv),
                         spread))
        trate = obj.get("tree_spec_acceptance_rate")
        if isinstance(trate, (int, float)):
            rows.append(("tree_spec_acceptance_rate", float(trate), 0.0))
        return rows
    if kind == "serve_plan":
        # the serving-plan leg (`bench.py --serve --plan-serve`): the
        # measured tokens/s under the SEARCHED plan is the headline
        # (higher-is-better), and the replay model's
        # predicted-vs-measured error is the honesty series
        # (lower-is-better in absolute points, like the plan record's).
        # Pre-ServePlan history artifacts carry neither series — the
        # per-series comparison SKIPs the new series only.
        if obj.get("status") == "SKIP":
            return []
        v = obj.get("measured_tokens_per_s")
        if not isinstance(v, (int, float)):
            raise ValueError(
                f"{label}: OK serve_plan record has no numeric "
                "measured_tokens_per_s")
        spread = obj.get("spread_pct")
        spread = float(spread) if isinstance(spread, (int, float)) else 0.0
        rows = [("serve_plan_tokens_per_s", float(v), spread)]
        err = obj.get("predicted_vs_measured_err_pct")
        if not isinstance(err, (int, float)):
            raise ValueError(
                f"{label}: OK serve_plan record has no numeric "
                "predicted_vs_measured_err_pct")
        # the record's spread_pct is throughput variance; it says
        # nothing about the model error, so it must not widen that gate
        rows.append(("serve_plan_predicted_vs_measured_err_pct",
                     float(err), 0.0))
        return rows
    if kind == "ckpt":
        # the checkpoint leg's gated series is its measured per-step
        # save overhead — lower-is-better in absolute points (a clean
        # async saver's reference is ~0%, so percent drift is undefined)
        if obj.get("status") == "SKIP":
            return []
        v = obj.get("save_overhead_pct")
        if not isinstance(v, (int, float)):
            raise ValueError(
                f"{label}: OK ckpt record has no numeric "
                "save_overhead_pct")
        return [("ckpt_save_overhead_pct", float(v), 0.0)]
    if kind is not None:
        return []  # other monitor records carry no headline number
    raise ValueError(
        f"{label}: unrecognized artifact shape (no metric/parsed/kind)")


def extract(obj: Dict[str, Any], label: str = "artifact"
            ) -> Optional[Tuple[str, float, float]]:
    """The artifact's PRIMARY claim — first row of :func:`extract_all`
    (None when it claims nothing)."""
    rows = extract_all(obj, label)
    return rows[0] if rows else None


def load_json(path: str) -> Any:
    with open(path) as fh:
        text = fh.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        # a JSONL stream: prefer the LAST record that carries a claim
        # shape (bench prints its record as the final line, but a
        # telemetry stream may trail with windows/meta); fall back to
        # the last parseable record
        last = claimed = None
        for lineno, line in enumerate(text.splitlines(), 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid JSON: {e}")
            last = obj
            if isinstance(obj, dict) and (
                    "metric" in obj
                    or obj.get("kind") in _THROUGHPUT_KINDS
                    or obj.get("kind") in ("plan", "serve_plan", "ckpt",
                                           "spec")):
                claimed = obj
        if last is None:
            raise ValueError(f"{path}: empty file")
        return claimed if claimed is not None else last


def _history_order(path: str) -> Tuple[int, str]:
    """Sort key putting BENCH_r2 before BENCH_r10 (numeric rounds)."""
    m = re.search(r"r(\d+)", os.path.basename(path))
    return (int(m.group(1)) if m else -1, path)


def collect_history(pattern: str, root: str) -> List[Tuple[str, str, float,
                                                           float]]:
    """[(path, metric, value, spread_pct)] for every gated series of
    every history artifact matching ``pattern``, in trajectory order
    (one artifact can carry several series — throughput AND latency)."""
    rows = []
    for path in sorted(glob.glob(os.path.join(root, pattern)),
                       key=_history_order):
        try:
            got = extract_all(load_json(path), path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"warning: skipping unreadable history {path}: {e}",
                  file=sys.stderr)
            continue
        rows.extend((path, *row) for row in got)
    return rows


def schema_problems(obj: Any, label: str) -> List[str]:
    """Validate one artifact through the shared monitor schema (driver
    envelopes unwrap; bench objects use BENCH_SCHEMA)."""
    if isinstance(obj, dict) and isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    if isinstance(obj, dict) and "kind" in obj:
        return [f"{label}: {e}" for e in schema.validate(obj)]
    if isinstance(obj, dict) and "metric" in obj:
        return [f"{label}: {e}"
                for e in schema.validate(obj, schema.BENCH_SCHEMA)]
    return [f"{label}: unrecognized artifact shape"]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python tools/bench_history.py",
        description="compare a fresh bench/serve artifact against the "
                    "BENCH_r*.json trajectory")
    parser.add_argument("fresh", help="fresh artifact (bench JSON line, "
                        "driver envelope, or monitor record/stream)")
    parser.add_argument("--history", default="BENCH_r*.json",
                        help="glob for the history trajectory, relative "
                             "to --root (default: BENCH_r*.json)")
    parser.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root holding the history artifacts")
    parser.add_argument("--tolerance-pct", type=float, default=3.0,
                        help="base tolerance before the artifacts' own "
                             "spread widens it (default 3%%)")
    parser.add_argument("--schema-only", action="store_true",
                        help="validate fresh + history shapes through "
                             "the monitor schema; no comparison (the "
                             "off-TPU tier-1 smoke)")
    args = parser.parse_args(argv)

    try:
        fresh_obj = load_json(args.fresh)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot read fresh artifact: {e}", file=sys.stderr)
        return 2

    if args.schema_only:
        problems = schema_problems(fresh_obj, args.fresh)
        for path in sorted(glob.glob(os.path.join(args.root, args.history)),
                           key=_history_order):
            try:
                problems.extend(schema_problems(load_json(path), path))
            except (OSError, ValueError, json.JSONDecodeError) as e:
                # a truncated artifact is a diagnostic line, not a
                # traceback — CI keys on exit 2 = broken artifact
                problems.append(f"{path}: unreadable: {e}")
        for p in problems:
            print(p, file=sys.stderr)
        if problems:
            return 2
        print(f"SCHEMA-ONLY OK: {args.fresh} + "
              f"{len(glob.glob(os.path.join(args.root, args.history)))} "
              f"history artifact(s) validate")
        return 0

    try:
        fresh_rows = extract_all(fresh_obj, args.fresh)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not fresh_rows:
        print(f"SKIP: {args.fresh} carries no throughput claim "
              f"(SKIP record) — nothing to gate")
        return 0

    all_history = collect_history(args.history, args.root)
    rc = 0
    for metric, value, fresh_spread in fresh_rows:
        history = [row for row in all_history if row[1] == metric]
        if not history:
            print(f"SKIP: no history artifact carries metric {metric!r} "
                  f"(glob {args.history}) — nothing to compare against")
            continue
        ref_path, _, ref_value, ref_spread = history[-1]
        rc = max(rc, _gate_series(
            metric, value, fresh_spread, ref_path, ref_value, ref_spread,
            args.tolerance_pct, len(history)))
    return rc


def _gate_series(metric: str, value: float, fresh_spread: float,
                 ref_path: str, ref_value: float, ref_spread: float,
                 tol: float, npoints: int) -> int:
    """Gate ONE series against its trajectory reference and print the
    one-line verdict; returns 0/1. Three direction/unit conventions
    share this shape: the plan-error series drifts UP in absolute
    points (the reference may legitimately be ~0%), lower-is-better
    latency series drift UP in percent, throughput drifts DOWN in
    percent."""
    budget = _ABSOLUTE_BUDGET.get(metric)
    if budget is not None and value > budget:
        print(f"REGRESSION {metric}: {value:g} exceeds the absolute "
              f"budget {budget:g}")
        return 1
    allowed = tol + fresh_spread + ref_spread
    ref = os.path.basename(ref_path)
    spread_note = (f" = tol {tol:g} + spread "
                   f"{ref_spread:g}+{fresh_spread:g}")
    if metric in _LOWER_IS_BETTER:
        delta = value - ref_value
        bad = delta > allowed
        detail_bad = f"(+{delta:.2f} pts > allowed +{allowed:.2f})"
        detail_ok = f"({delta:+.2f} pts, allowed +{allowed:.2f})"
    else:
        delta_pct = 100.0 * (value - ref_value) / ref_value
        if metric in _LOWER_IS_BETTER_PCT:
            bad = delta_pct > allowed
            detail_bad = (f"({delta_pct:+.2f}% > allowed "
                          f"+{allowed:.2f}%{spread_note})")
            detail_ok = f"({delta_pct:+.2f}%, allowed +{allowed:.2f}%)"
        else:
            bad = delta_pct < -allowed
            detail_bad = (f"({delta_pct:+.2f}% < allowed "
                          f"-{allowed:.2f}%{spread_note})")
            detail_ok = f"({delta_pct:+.2f}%, allowed -{allowed:.2f}%)"
    if bad:
        print(f"REGRESSION {metric}: {value:g} vs {ref} {ref_value:g} "
              f"{detail_bad}")
        return 1
    print(f"OK {metric}: {value:g} vs {ref} {ref_value:g} {detail_ok} "
          f"over {npoints}-point trajectory")
    return 0


if __name__ == "__main__":
    sys.exit(main())
