"""Capture a jax.profiler trace of the flagship GPT train step on TPU and
print the per-op report — VERDICT r1 item 6's acceptance run:

    python tools/profile_bench.py [logdir]

Produces the top-5 device time sinks + per-family roofline table via
``apex_tpu.prof`` (the pyprof analog working on a *real* trace).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.random as jr


def main():
    logdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/apex_tpu_prof"
    import optax

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.prof import trace
    from apex_tpu.prof.trace_reader import format_report

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = GPTConfig(vocab_size=32768, max_seq_len=1024, hidden_size=1024,
                        num_layers=12, num_heads=8, remat=False,
                        attention_impl="flash", scan_layers=False)
        batch, seq = 20, 1024
    else:
        cfg = GPTConfig(vocab_size=1024, max_seq_len=128, hidden_size=128,
                        num_layers=2, num_heads=4, remat=True,
                        attention_impl="flash")
        batch, seq = 2, 128

    model = GPTModel(cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                          model.init(jr.PRNGKey(0)))
    opt = fused_adam(1e-4)
    opt_state = opt.init(params)
    tokens = jr.randint(jr.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)
    targets = jr.randint(jr.PRNGKey(2), (batch, seq), 0, cfg.vocab_size)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss_fn)(params, tokens, targets)
        updates, opt_state = opt.update(grads, opt_state, params)
        import optax as _o
        return _o.apply_updates(params, updates), opt_state, loss

    params, opt_state, loss = step(params, opt_state, tokens, targets)
    float(loss)

    with trace(logdir):
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tokens, targets)
        float(loss)

    print(format_report(logdir, top=5))


if __name__ == "__main__":
    main()
